"""Fleet telemetry plane: cluster-joined traces, SLO burn rates, event journal.

PR 7 made one process legible — per-op spans, a flight recorder, ``GET
/trace``, latency histograms — but the system the ROADMAP steers toward
(elastic multi-member clusters) fails at the *fleet* level: a breaker trips
on member 2, a reshard epoch bumps, foreground p99 drifts, and each of
those is visible only as a disconnected counter on one process's manage
plane. This module joins them (docs/observability.md, fleet section):

- :class:`EventJournal` — a bounded structured ring of **cluster events**
  (the :data:`EVENT_KINDS` vocabulary: breaker transitions, membership
  epoch changes, stripe quarantine/revive, watchdog slow ops, QoS aging
  storms, SLO alert edges), each stamped with member id, epoch, and the
  ACTIVE TRACE ID where one exists — so "why was this op slow" joins the
  op's span tree to the cluster state change that slowed it. Served at
  ``GET /events`` and cross-linked from ``GET /trace``.
- :class:`SloEngine` — rolling multi-window SLIs (availability, fg p99
  from the ``infinistore_op_duration_us`` histograms, miss rate, reshard
  debt drain) with **multi-window burn-rate alerting** (short AND long
  window over threshold fires; hysteresis clears). Exported as
  ``infinistore_slo_*`` gauges and the ``GET /slo`` verdict consumed by
  ``/health``. Clock-injectable: the window math is tested with a fake
  clock, no sleeps.
- :class:`FleetScraper` — an off-loop, breaker-aware, bounded scraper that
  pulls each member's ``/trace`` (native tick ring + flight-recorder
  spans) and ``/stats`` (op counters + histograms) over the manage plane,
  feeds the SLO engine with the deltas, and keeps the last per-member
  span set for the **cluster trace join**: ``GET /trace?scope=cluster``
  merges every member's spans with the local client recorder by trace id
  onto one monotonic timeline (same-host CLOCK_MONOTONIC; one Perfetto
  track lane per member in ``?fmt=chrome``).

The ITS-C006 checker (tools/analysis/counters.py) holds the telemetry
vocabulary in lockstep: every :data:`EVENT_KINDS` entry must have a
producer and a docs row, every ``slo_*`` status key must reach the
``/metrics`` exporter, and the manage plane must keep serving ``/slo`` and
``/events``.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import tracing

# ---------------------------------------------------------------------------
# Event journal.
# ---------------------------------------------------------------------------

# Canonical cluster-event vocabulary. The ITS-C006 checker fails the build
# when a producer emits a kind outside this tuple, when a kind has no
# producer left (dead vocabulary), or when a kind is undocumented in
# docs/observability.md.
EVENT_KINDS = (
    "breaker_open",       # member breaker tripped (CLOSED/HALF_OPEN -> OPEN)
    "breaker_half_open",  # probe window elapsed; one probe admitted
    "breaker_closed",     # probe success re-closed the breaker (recovery)
    "membership_epoch",   # membership transition bumped the epoch
    "stripe_quarantine",  # striped data plane quarantined a dead stripe
    "stripe_revive",      # quarantined stripe reconnected and rejoined
    "slow_op",            # watchdog captured an over-threshold span tree
    "qos_aging_storm",    # bg aging escapes crossed the storm threshold
    "slo_alert",          # burn-rate alert fired or cleared (edge)
    "gossip_round",       # one anti-entropy peer-exchange round completed
    "client_restart",     # a crashed client replayed its durable journal
    "tier_demotion",      # an idle root's copy shipped to the pooled cold tier
    "tier_promotion",     # a reused cold root copied back to its serving owner
    "metric_anomaly",     # metrics-history change-point detector fired
    "disagg_fallback",    # handoff layer late/failed -> local recompute leg
)

_DEFAULT_JOURNAL_CAPACITY = 512


class EventJournal:
    """Bounded structured ring of cluster events (causal journal).

    Always on and cheap: events are rare (state transitions, not ops), one
    lock-guarded append each. Every event records ``seq`` (monotone),
    ``t_us`` (CLOCK_MONOTONIC microseconds — the same clock trace spans
    stamp, so events sort onto the trace timeline), wall-clock seconds,
    the event ``kind``, the ``member`` id and membership ``epoch`` where
    known, and the active ``trace_id`` when the emitting code ran inside
    a traced op — that link is what makes the journal *causal* rather
    than a log.
    """

    def __init__(self, capacity: int = _DEFAULT_JOURNAL_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        # its: guard[_events, _seq, emitted, _counts: _lock]
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.emitted = 0
        self._counts: Dict[str, int] = {}

    def emit(self, kind: str, member: str = "", epoch: int = 0,
             trace_id: Optional[int] = None, **attrs) -> dict:
        """Record one event. ``trace_id=None`` stamps the active span's
        trace id (0 when untraced); pass an explicit id when emitting on
        behalf of another context (the slow-op hook)."""
        if trace_id is None:
            span = tracing.active_span()
            trace_id = span.trace_id if span is not None else 0
        event = {
            "kind": kind,
            "member": member,
            "epoch": int(epoch),
            "trace_id": int(trace_id),
            "t_us": tracing._now_us(),
            "wall_s": round(time.time(), 3),
            "attrs": attrs,
        }
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
            self.emitted += 1
            self._counts[kind] = self._counts.get(kind, 0) + 1
        return event

    def snapshot(self, since_seq: int = 0,
                 limit: Optional[int] = None) -> List[dict]:
        """Events with ``seq > since_seq``, oldest first (ring-bounded)."""
        with self._lock:
            out = [dict(e) for e in self._events if e["seq"] > since_seq]
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def for_trace(self, trace_ids) -> List[dict]:
        """Events carrying one of ``trace_ids`` — the /trace cross-link."""
        wanted = set(trace_ids)
        with self._lock:
            return [dict(e) for e in self._events if e["trace_id"] in wanted]

    def counts(self) -> Dict[str, int]:
        """Per-kind emit totals (``infinistore_events_total`` on /metrics;
        counts survive ring eviction)."""
        with self._lock:
            return dict(self._counts)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._seq = 0
            self.emitted = 0
            self._counts = {}


class _StormDetector:
    """Edge-triggered rate detector for QoS aging escapes: emits one
    ``qos_aging_storm`` event when ``threshold`` escapes land within
    ``window_s``, then re-arms only after a full quiet window (hysteresis
    — a sustained storm is one event, not a flood of them)."""

    def __init__(self, threshold: int = 64, window_s: float = 1.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.window_s = window_s
        self._clock = clock
        self._stamps: deque = deque()
        self._armed = True
        self._lock = threading.Lock()

    def note(self, n: int = 1) -> int:
        """Record ``n`` aging escapes; returns the in-window count when a
        storm edge fired, else 0."""
        now = self._clock()
        with self._lock:
            horizon = now - self.window_s
            while self._stamps and self._stamps[0] < horizon:
                self._stamps.popleft()
            # Re-arm BEFORE recording this note's escapes: an empty window
            # here means a full quiet window elapsed since the last storm
            # — checking after the append could never see zero from the
            # production callers (which always note >= 1).
            if not self._armed and not self._stamps:
                self._armed = True
            for _ in range(n):
                self._stamps.append(now)
            count = len(self._stamps)
            if self._armed and count >= self.threshold:
                self._armed = False
                return count
            return 0


# ---------------------------------------------------------------------------
# SLO engine: rolling multi-window SLIs + burn-rate alerting.
# ---------------------------------------------------------------------------

class SloObjective:
    """One SLO: a good/bad ratio target (``kind="ratio"``) or a latency
    objective (``kind="latency"``: a sample is *bad* when it lands in a
    histogram bucket above ``latency_threshold_us``; the windowed p99 is
    kept alongside for display). ``target`` is the success-ratio
    objective (e.g. 0.999); the error budget is ``1 - target``."""

    def __init__(self, name: str, target: float, kind: str = "ratio",
                 latency_threshold_us: float = 0.0):
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if kind not in ("ratio", "latency"):
            raise ValueError(f"unknown objective kind {kind!r}")
        self.name = name
        self.target = target
        self.kind = kind
        self.latency_threshold_us = latency_threshold_us


def default_objectives() -> List[SloObjective]:
    """The fleet's standing SLO set (docs/observability.md):
    availability of data-plane ops, foreground p99 (from the per-op
    duration histograms), cache miss rate through the degrade machinery,
    and reshard debt drain (a reshard whose debt stops draining is an
    incident even though every individual op succeeds)."""
    return [
        SloObjective("availability", target=0.999),
        SloObjective("fg_latency", target=0.99, kind="latency",
                     latency_threshold_us=50_000.0),
        SloObjective("miss_rate", target=0.90),
        SloObjective("reshard_drain", target=0.90),
        # Pooled-cold-tier read latency (docs/tiering.md): cold reads are
        # allowed to be slow — they exist to beat recompute, not RAM — but
        # a cold read slower than ~0.5s has likely stopped doing that.
        # Fed by the cluster's cold-load fall-through
        # (tiering.note_cold_read_us).
        SloObjective("cold_latency", target=0.95, kind="latency",
                     latency_threshold_us=500_000.0),
    ]


# Multi-window burn-rate rules (SRE-workbook shape): (short_s, long_s,
# burn_threshold). An alert FIRES when the burn rate exceeds the threshold
# over BOTH windows — the long window proves the budget spend is real, the
# short window proves it is still happening — and stays firing until the
# short-window burn drops below ``clear_ratio * threshold`` (hysteresis).
DEFAULT_BURN_WINDOWS: Tuple[Tuple[float, float, float], ...] = (
    (300.0, 3600.0, 14.4),   # fast burn: 2% of a 30d budget in 1h
    (1800.0, 21600.0, 6.0),  # slow burn: 5% of a 30d budget in 6h
)


class SloEngine:
    """Rolling multi-window SLI store + burn-rate alert evaluator.

    Samples land in coarse time buckets (``bucket_s``) per objective; a
    window SLI is the good/bad ratio over the buckets it covers, so
    memory is O(windows/bucket_s) per objective regardless of traffic.
    The clock is injectable and nothing sleeps — the window math
    (roll-off, burn monotonicity, hysteresis) is property-tested with a
    fake clock (tests/test_telemetry.py).

    Key vocabulary: :meth:`status` returns the flat ``slo_*`` snapshot the
    ``/slo`` endpoint serves and ``server._slo_prometheus_lines`` exports
    — held in lockstep by ITS-C006.
    """

    def __init__(self, objectives: Optional[Sequence[SloObjective]] = None,
                 windows: Sequence[Tuple[float, float, float]] = DEFAULT_BURN_WINDOWS,
                 clear_ratio: float = 0.5,
                 bucket_s: float = 5.0,
                 clock=time.monotonic,
                 journal: Optional[EventJournal] = None):
        self.objectives: Dict[str, SloObjective] = {
            o.name: o for o in (objectives if objectives is not None
                                else default_objectives())
        }
        self.windows = tuple(windows)
        self.clear_ratio = clear_ratio
        self.bucket_s = bucket_s
        self._clock = clock
        self._journal = journal
        self._max_window = max((w[1] for w in self.windows), default=3600.0)
        self._lock = threading.Lock()
        # name -> deque[[bucket_start_s, good, bad]]
        # its: guard[_buckets, _lat, _firing: _lock]
        self._buckets: Dict[str, deque] = {}
        # latency objectives: name -> deque[[bucket_start_s, {le_us: count}]]
        self._lat: Dict[str, deque] = {}
        # (objective, long_s) -> firing bool; plus the fire-edge counter.
        self._firing: Dict[Tuple[str, float], bool] = {}
        # its: guard[alerts_total: _lock!w]
        self.alerts_total = 0

    # -- feeding -------------------------------------------------------------

    def _bucket(self, store: Dict[str, deque], name: str, now: float,
                empty) -> list:  # its: requires[_lock]
        dq = store.setdefault(name, deque())
        start = now - (now % self.bucket_s)
        if not dq or dq[-1][0] != start:
            dq.append([start, *empty()])
        horizon = now - self._max_window - self.bucket_s
        while dq and dq[0][0] < horizon:
            dq.popleft()
        return dq[-1]

    def record(self, name: str, good: int = 0, bad: int = 0,
               t: Optional[float] = None):
        """Feed good/bad samples to a ratio objective (unknown names are
        accepted — the objective may be configured later; they simply
        don't alert until it is)."""
        now = self._clock() if t is None else t
        with self._lock:
            b = self._bucket(self._buckets, name, now, lambda: (0, 0))
            b[1] += good
            b[2] += bad

    def record_latency_bucket(self, name: str, le_us: float, count: int = 1,
                              t: Optional[float] = None):
        """Feed ``count`` latency samples whose upper bucket bound is
        ``le_us`` (the scraper feeds histogram DELTAS between scrapes).
        Samples above the objective's threshold count against the budget;
        the windowed p99 is derived from the same buckets."""
        if count <= 0:
            return
        now = self._clock() if t is None else t
        obj = self.objectives.get(name)
        threshold = obj.latency_threshold_us if obj is not None else 0.0
        with self._lock:
            lb = self._bucket(self._lat, name, now, lambda: ({},))
            hist = lb[1]
            hist[float(le_us)] = hist.get(float(le_us), 0) + count
            b = self._bucket(self._buckets, name, now, lambda: (0, 0))
            if threshold and le_us > threshold:
                b[2] += count
            else:
                b[1] += count

    # -- window math ---------------------------------------------------------

    def _window_counts(self, name: str, window_s: float,
                       now: float) -> Tuple[int, int]:  # its: requires[_lock]
        dq = self._buckets.get(name)
        if not dq:
            return 0, 0
        horizon = now - window_s
        good = bad = 0
        for start, g, b in dq:
            if start + self.bucket_s > horizon:
                good += g
                bad += b
        return good, bad

    def sli(self, name: str, window_s: Optional[float] = None,
            now: Optional[float] = None) -> float:
        """Success ratio over the window (1.0 with no samples — an idle
        SLI is a met SLI, not a firing one)."""
        now = self._clock() if now is None else now
        window_s = self._max_window if window_s is None else window_s
        with self._lock:
            good, bad = self._window_counts(name, window_s, now)
        total = good + bad
        return 1.0 if total == 0 else good / total

    def burn_rate(self, name: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """Error-budget burn multiple over the window: observed bad
        fraction / allowed bad fraction (1.0 = spending exactly on
        budget; 14.4 = a 30d budget gone in 50h)."""
        obj = self.objectives.get(name)
        if obj is None:
            return 0.0
        now = self._clock() if now is None else now
        with self._lock:
            good, bad = self._window_counts(name, window_s, now)
        total = good + bad
        if total == 0:
            return 0.0
        budget = 1.0 - obj.target
        return (bad / total) / budget if budget > 0 else 0.0

    def p99_us(self, name: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> float:
        """Windowed p99 for a latency objective, from its bucket counts
        (upper bucket bound, the same convention the /metrics histogram
        export uses). 0.0 with no samples."""
        now = self._clock() if now is None else now
        window_s = self._max_window if window_s is None else window_s
        with self._lock:
            dq = self._lat.get(name)
            if not dq:
                return 0.0
            horizon = now - window_s
            merged: Dict[float, int] = {}
            for start, hist in dq:
                if start + self.bucket_s > horizon:
                    for le, cnt in hist.items():
                        merged[le] = merged.get(le, 0) + cnt
        total = sum(merged.values())
        if total == 0:
            return 0.0
        goal = 0.99 * total
        cum = 0
        for le in sorted(merged):
            cum += merged[le]
            if cum >= goal:
                return le
        return max(merged)

    # -- alerting ------------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Evaluate every (objective, rule) pair; returns the FIRING alert
        list and emits ``slo_alert`` journal events on fire/clear edges.
        Hysteresis: a firing alert needs the short-window burn to drop
        below ``clear_ratio * threshold`` to clear — not merely below the
        threshold — so an alert flapping on the fire line stays up."""
        now = self._clock() if now is None else now
        firing: List[dict] = []
        for name in self.objectives:
            for short_s, long_s, threshold in self.windows:
                short = self.burn_rate(name, short_s, now)
                long = self.burn_rate(name, long_s, now)
                key = (name, long_s)
                # The fire/clear edge is check-then-act shared between the
                # scraper daemon thread and the manage plane's /slo//health
                # handlers: take it under the engine lock so a concurrent
                # evaluate() cannot double-count alerts_total or journal a
                # duplicate edge. The emit itself stays OUTSIDE the lock
                # (the journal has its own), same discipline as the
                # cluster breaker edges.
                with self._lock:
                    was = self._firing.get(key, False)
                    if was:
                        is_firing = short >= self.clear_ratio * threshold
                    else:
                        is_firing = short >= threshold and long >= threshold
                    edge = is_firing != was
                    if edge:
                        self._firing[key] = is_firing
                        if is_firing:
                            self.alerts_total += 1
                if edge and self._journal is not None:
                    self._journal.emit(
                        "slo_alert", objective=name,
                        window_s=long_s, state=(
                            "firing" if is_firing else "cleared"
                        ),
                        burn_short=round(short, 3),
                        burn_long=round(long, 3),
                    )
                if is_firing:
                    firing.append({
                        "objective": name,
                        "short_window_s": short_s,
                        "long_window_s": long_s,
                        "threshold": threshold,
                        "burn_short": round(short, 4),
                        "burn_long": round(long, 4),
                    })
        return firing

    def status(self, now: Optional[float] = None) -> dict:
        """The ``/slo`` verdict payload. Flat ``slo_*`` keys are the gauge
        vocabulary ``_slo_prometheus_lines`` exports (ITS-C006);
        ``objectives``/``alerts`` carry the per-objective detail."""
        now = self._clock() if now is None else now
        alerts = self.evaluate(now)
        detail = {}
        burn_max = 0.0
        for name, obj in self.objectives.items():
            burns = {}
            for short_s, long_s, threshold in self.windows:
                burns[f"{int(short_s)}s"] = round(
                    self.burn_rate(name, short_s, now), 4
                )
                burns[f"{int(long_s)}s"] = round(
                    self.burn_rate(name, long_s, now), 4
                )
                # Max over BOTH windows: a burst that ended minutes ago has
                # a zero short-window burn while the long window still
                # shows the budget spent — the max gauge must not go clean
                # before the labeled long-window gauge does.
                burn_max = max(
                    burn_max,
                    burns[f"{int(short_s)}s"],
                    burns[f"{int(long_s)}s"],
                )
            detail[name] = {
                "kind": obj.kind,
                "target": obj.target,
                "sli": round(self.sli(name, now=now), 6),
                "burn_rates": burns,
            }
            if obj.kind == "latency":
                detail[name]["p99_us"] = self.p99_us(name, now=now)
        return {
            "slo_availability": round(self.sli("availability", now=now), 6),
            "slo_fg_p99_us": round(self.p99_us("fg_latency", now=now), 1),
            "slo_cold_p99_us": round(self.p99_us("cold_latency", now=now), 1),
            "slo_miss_rate": round(1.0 - self.sli("miss_rate", now=now), 6),
            "slo_reshard_drain": round(self.sli("reshard_drain", now=now), 6),
            "slo_burn_rate_max": round(burn_max, 4),
            "slo_alerts_firing": len(alerts),
            "slo_alerts_total": self.alerts_total,
            "verdict": "burning" if alerts else "ok",
            "objectives": detail,
            "alerts": alerts,
        }


# ---------------------------------------------------------------------------
# Fleet scraper: off-loop, breaker-aware, bounded.
# ---------------------------------------------------------------------------

class _TargetState:
    """Per-target scrape bookkeeping + a minimal availability breaker:
    after ``fail_threshold`` consecutive scrape failures the target is
    skipped until ``backoff_s`` elapses (one probe per window — a dead
    member must cost the scraper one timeout per window, not one per
    scrape)."""

    def __init__(self, member_id: str, host: str, manage_port: int):
        self.member_id = member_id
        self.host = host
        self.manage_port = manage_port
        self.consecutive_failures = 0
        self.skip_until = 0.0
        self.last_ok_at = 0.0
        self.scrapes = 0
        self.failures = 0
        self.last_error = ""
        # Cumulative op counters at the last scrape (delta source).
        self.prev_ops: Dict[str, dict] = {}
        self.prev_suspended = 0
        self.ops_per_s = 0.0
        self.queue_depth = 0
        self.spans: List[dict] = []


class FleetScraper:
    """Pulls each member's manage plane (``/trace`` + ``/stats``), feeds
    the SLO engine with counter/histogram deltas, and keeps the last
    per-member span set for the cluster trace join.

    Off-loop by construction: :meth:`scrape_once` does blocking HTTP and
    is called either from the background thread (:meth:`start`) or via
    ``asyncio.to_thread`` (the manage plane's ``scope=cluster`` handler).
    Bounded: per-member spans are capped at ``max_spans_per_member`` and
    response bodies at ``max_body_bytes``. Breaker-aware: a target that
    keeps failing is skipped until its backoff elapses (see
    :class:`_TargetState`).
    """

    def __init__(self, targets: Sequence[Tuple[str, str, int]] = (),
                 slo: Optional[SloEngine] = None,
                 journal: Optional[EventJournal] = None,
                 cluster=None,
                 interval_s: float = 5.0,
                 timeout_s: float = 2.0,
                 max_spans_per_member: int = 512,
                 max_body_bytes: int = 4 << 20,
                 fail_threshold: int = 3,
                 backoff_s: float = 10.0,
                 clock=time.monotonic):
        self.slo = slo if slo is not None else slo_engine()
        self.journal = journal if journal is not None else get_journal()
        self.cluster = cluster
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.max_spans_per_member = max_spans_per_member
        self.max_body_bytes = max_body_bytes
        self.fail_threshold = fail_threshold
        self.backoff_s = backoff_s
        self._clock = clock
        # its: guard[_targets: _lock]
        self._targets: List[_TargetState] = []
        self._lock = threading.Lock()
        # Serializes whole scrape passes: the background thread and an
        # on-demand ?scope=cluster refresh (asyncio.to_thread) must never
        # delta the same prev_ops concurrently — that would feed the same
        # op counters to the SLO engine twice.
        self._pass_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # its: guard[scrapes_total, scrape_failures_total: _pass_lock!w]
        self.scrapes_total = 0
        self.scrape_failures_total = 0
        # its: guard[_prev_debt: _pass_lock]
        self._prev_debt: Optional[int] = None
        for t in targets:
            self.add_target(*t)

    def add_target(self, member_id: str, host: str, manage_port: int):
        with self._lock:
            self._targets.append(_TargetState(member_id, host, manage_port))

    # -- one scrape pass -----------------------------------------------------

    def _get_json(self, st: _TargetState, path: str) -> dict:
        url = f"http://{st.host}:{st.manage_port}{path}"
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            body = resp.read(self.max_body_bytes)
        return json.loads(body)

    def _feed_stats(self, st: _TargetState, stats: dict, now: float):
        """Delta the member's cumulative op counters/histograms into the
        SLO engine: ok/error deltas feed availability, histogram bucket
        deltas feed the fg-latency objective.

        With a cluster attached, the availability feed is SKIPPED: the
        cluster already records every op outcome client-side (including
        the fast-fails a dead member's scrape can never show), and
        double-feeding the served ops from server counters would dilute
        the bad fraction ~2x — a burn-rate alert firing at half strength
        during an outage. Scrape-fed availability is the standalone
        deployment's source (no cluster object in-process)."""
        ops = stats.get("ops", {}) or {}
        total_delta = 0
        for op, s in ops.items():
            prev = st.prev_ops.get(op, {})
            d_count = s.get("count", 0) - prev.get("count", 0)
            d_err = s.get("errors", 0) - prev.get("errors", 0)
            if d_count < 0:  # member restarted: counters reset
                prev, d_count, d_err = {}, s.get("count", 0), s.get("errors", 0)
            if d_count > 0:
                total_delta += d_count
                if self.cluster is None:
                    self.slo.record(
                        "availability",
                        good=max(0, d_count - d_err), bad=max(0, d_err),
                    )
            prev_hist = dict(prev.get("hist", []))
            for le, cnt in s.get("hist_us", []):
                d = cnt - prev_hist.get(le, 0)
                if d > 0:
                    self.slo.record_latency_bucket("fg_latency", le, d)
            st.prev_ops[op] = {
                "count": s.get("count", 0),
                "errors": s.get("errors", 0),
                "hist": [(le, cnt) for le, cnt in s.get("hist_us", [])],
            }
        if st.last_ok_at:
            dt = max(1e-6, now - st.last_ok_at)
            st.ops_per_s = total_delta / dt
        st.queue_depth = stats.get("suspended_ops", 0)

    def _feed_cluster(self):  # its: requires[_pass_lock]
        """Reshard-drain SLI from the attached cluster: a scrape tick is
        GOOD when the migration debt is zero or shrinking, BAD when debt
        exists and did not drain since the last look."""
        if self.cluster is None:
            return
        try:
            debt = int(
                self.cluster.membership_status().get("reshard_debt_roots", 0)
            )
        except Exception:
            return
        prev = self._prev_debt
        self._prev_debt = debt
        if debt == 0:
            self.slo.record("reshard_drain", good=1)
        elif prev is not None and debt < prev:
            self.slo.record("reshard_drain", good=1)
        elif prev is not None:
            self.slo.record("reshard_drain", bad=1)

    def scrape_once(self, spans: bool = True) -> dict:
        """One blocking pass over every admitted target (callers keep this
        OFF the event loop; concurrent passes serialize — the second runs
        after the first and sees zero deltas). Returns a scrape summary.

        ``spans=False`` pulls only ``/stats`` (the SLO feed) and keeps each
        target's previously-held spans: the span dump is by far the
        expensive half of a pass, and its only consumer —
        ``GET /trace?scope=cluster`` — forces a fresh full pass anyway, so
        the background loop never pays for it."""
        with self._pass_lock:
            return self._scrape_pass(spans)

    def _scrape_pass(self, want_spans: bool = True) -> dict:  # its: requires[_pass_lock]
        now = self._clock()
        ok = skipped = failed = 0
        with self._lock:
            targets = list(self._targets)
        for st in targets:
            if (
                st.consecutive_failures >= self.fail_threshold
                and now < st.skip_until
            ):
                skipped += 1
                continue
            try:
                stats = self._get_json(st, "/stats")
                spans = None
                if want_spans:
                    trace = self._get_json(st, "/trace")
                    spans = list(trace.get("spans", [])) + list(
                        trace.get("server_spans", [])
                    )
                    for s in spans:
                        s.setdefault("attrs", {})["member"] = st.member_id
                self._feed_stats(st, stats, now)
                with self._lock:
                    if spans is not None:
                        st.spans = spans[-self.max_spans_per_member:]
                    st.consecutive_failures = 0
                    st.last_ok_at = now
                    st.scrapes += 1
                ok += 1
                self.scrapes_total += 1
            # Broad by design: an unexpected-SHAPE payload (version skew, a
            # proxy answering the manage port) raises TypeError/KeyError in
            # the feed path, and it must count against THIS target's
            # breaker instead of aborting the rest of the pass.
            except Exception as e:
                failed += 1
                self.scrape_failures_total += 1
                with self._lock:
                    st.failures += 1
                    st.consecutive_failures += 1
                    st.last_error = repr(e)
                    if st.consecutive_failures >= self.fail_threshold:
                        st.skip_until = self._clock() + self.backoff_s
        self._feed_cluster()
        self.slo.evaluate()
        return {"ok": ok, "failed": failed, "skipped": skipped}

    # -- background loop -----------------------------------------------------

    def start(self):
        """Run :meth:`scrape_once` every ``interval_s`` on a daemon
        thread (the off-loop half of the manage plane's fleet view)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="its-fleet-scraper", daemon=True
        )
        self._thread.start()

    def _loop(self):
        # Scrape immediately on entry — waiting a full interval first would
        # leave /slo serving empty member rows for interval_s after start().
        while True:
            try:
                self.scrape_once(spans=False)
            except Exception:
                # The scraper must never die to one bad payload; per-target
                # failures are already counted in scrape_once. Counter under
                # the pass lock: a concurrent on-demand pass increments the
                # same total (ITS-R001 guard discipline).
                with self._pass_lock:
                    self.scrape_failures_total += 1
            if self._stop.wait(self.interval_s):
                return

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # -- read side -----------------------------------------------------------

    def member_spans(self) -> Dict[str, List[dict]]:
        """Last-scrape span dicts per member, each tagged
        ``attrs.member`` (the cluster-trace-join input)."""
        with self._lock:
            return {st.member_id: list(st.spans) for st in self._targets}

    def status(self) -> dict:
        """Per-member scrape health for the ``/slo`` payload and
        ``tools.top``."""
        now = self._clock()
        with self._lock:
            members = [
                {
                    "member": st.member_id,
                    "target": f"{st.host}:{st.manage_port}",
                    "ok": st.consecutive_failures < self.fail_threshold,
                    "last_scrape_age_s": (
                        round(now - st.last_ok_at, 3) if st.last_ok_at else -1.0
                    ),
                    "scrapes": st.scrapes,
                    "failures": st.failures,
                    "consecutive_failures": st.consecutive_failures,
                    "ops_per_s": round(st.ops_per_s, 1),
                    "queue_depth": st.queue_depth,
                    "last_error": st.last_error,
                    "spans_held": len(st.spans),
                }
                for st in self._targets
            ]
        return {
            "interval_s": self.interval_s,
            "scrapes_total": self.scrapes_total,
            "scrape_failures_total": self.scrape_failures_total,
            "members": members,
        }


# ---------------------------------------------------------------------------
# Gossip agent: anti-entropy membership exchange over the manage plane.
# ---------------------------------------------------------------------------

class GossipAgent:
    """Anti-entropy membership exchange between cluster-client processes
    (docs/membership.md, gossip section).

    Each client process that owns a ``ClusterKVConnector`` runs one agent.
    A round POSTs the cluster's ``gossip_payload()`` (epoch-stamped view
    with per-entry incarnation stamps) to each admitted peer's manage
    plane (``POST /gossip``); the peer merges it through the tombstone-
    aware lattice and answers with ITS post-merge view, which this agent
    merges back — one exchange is **push-pull**, so an epoch bump on
    either side converges in a single round in either direction, with no
    operator POSTing ``/membership`` to every process.

    Peer discipline is the :class:`FleetScraper`'s, reusing
    :class:`_TargetState`: a peer that keeps failing is skipped until its
    backoff elapses (one probe per window — a dead peer costs one timeout
    per window, not one per round). Rounds are journaled as
    ``gossip_round`` events (with the active trace id where one exists)
    and counted in the ``gossip_*`` vocabulary :meth:`status` returns —
    exported as ``infinistore_gossip_*`` on /metrics and held in lockstep
    by ITS-C006.
    """

    def __init__(self, cluster, peers: Sequence[Tuple[str, str, int]] = (),
                 interval_s: float = 1.0, timeout_s: float = 2.0,
                 fail_threshold: int = 3, backoff_s: float = 10.0,
                 journal: Optional[EventJournal] = None,
                 clock=time.monotonic):
        """``peers``: ``(peer_id, host, manage_port)`` triples — the seed
        list of OTHER client processes' manage planes (not store service
        ports)."""
        self.cluster = cluster
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.fail_threshold = fail_threshold
        self.backoff_s = backoff_s
        self.journal = journal if journal is not None else get_journal()
        self._clock = clock
        self._lock = threading.Lock()
        # its: guard[_targets: _lock]
        self._targets: List[_TargetState] = []
        # Serializes whole gossip rounds (ITS-R audit, PR 13): the
        # background thread and a manual round (tools/fleet, tests) used
        # to interleave freely — double-counting the round ledger and
        # racing two merge_remote_view pulls of the same payload. The
        # FleetScraper grew the same pass lock in PR 8; this is the
        # gossip agent's missing post-review hardening.
        self._round_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # its: guard[rounds, exchanges, exchange_failures: _round_lock!w]
        self.rounds = 0
        self.exchanges = 0
        self.exchange_failures = 0
        # its: guard[merges_in, merges_out: _round_lock!w]
        self.merges_in = 0   # this process adopted a peer's knowledge
        self.merges_out = 0  # a peer adopted ours (its response said so)
        # its: guard[last_epoch_seen, last_round_ms: _round_lock!w]
        self.last_epoch_seen = 0
        self.last_round_ms = 0.0
        for p in peers:
            self.add_peer(*p)

    def add_peer(self, peer_id: str, host: str, manage_port: int):
        with self._lock:
            self._targets.append(_TargetState(peer_id, host, manage_port))

    def _post_gossip(self, st: _TargetState, payload: dict) -> dict:
        url = f"http://{st.host}:{st.manage_port}/gossip"
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read(4 << 20))

    def exchange_once(self) -> dict:
        """One gossip round over every admitted peer (blocking HTTP —
        callers keep this off the event loop; the background thread and
        tests drive it). Concurrent callers serialize on the round lock —
        the second round runs after the first (same discipline as the
        scraper's pass lock). Returns ``{"ok", "failed", "skipped",
        "adopted"}`` and journals one ``gossip_round`` event (emitted
        OUTSIDE the round lock — the ITS-R003 discipline)."""
        with self._round_lock:
            summary, epoch = self._exchange_round()
        self.journal.emit(
            "gossip_round", epoch=epoch, peers_ok=summary["ok"],
            peers_failed=summary["failed"], peers_skipped=summary["skipped"],
            adopted=summary["adopted"],
        )
        return summary

    def _exchange_round(self):  # its: requires[_round_lock]
        t0 = self._clock()
        payload = self.cluster.gossip_payload()
        ok = failed = skipped = 0
        adopted = 0
        with self._lock:
            targets = list(self._targets)
        for st in targets:
            now = self._clock()
            if (
                st.consecutive_failures >= self.fail_threshold
                and now < st.skip_until
            ):
                skipped += 1
                continue
            try:
                doc = self._post_gossip(st, payload)
                self.exchanges += 1
                if doc.get("merged"):
                    self.merges_out += 1
                self.last_epoch_seen = max(
                    self.last_epoch_seen, int(doc.get("epoch", 0))
                )
                # The pull half: merge the peer's (post-merge) view. A
                # stale view of OURS comes back corrected here — the
                # structured response body is the self-correction channel.
                if doc.get("members") and self.cluster.merge_remote_view(doc):
                    adopted += 1
                    self.merges_in += 1
                    payload = self.cluster.gossip_payload()
                with self._lock:
                    st.consecutive_failures = 0
                    st.last_ok_at = now
                    st.scrapes += 1
                ok += 1
            # Broad like the scraper: a peer answering with an unexpected
            # shape (or a structured 4xx error body) must count against
            # THAT peer's breaker, not abort the round.
            except Exception as e:
                failed += 1
                self.exchange_failures += 1
                with self._lock:
                    st.failures += 1
                    st.consecutive_failures += 1
                    st.last_error = repr(e)
                    if st.consecutive_failures >= self.fail_threshold:
                        st.skip_until = self._clock() + self.backoff_s
        self.rounds += 1
        self.last_round_ms = round((self._clock() - t0) * 1e3, 3)
        epoch = int(self.cluster.membership.view().epoch)
        self.last_epoch_seen = max(self.last_epoch_seen, epoch)
        return {"ok": ok, "failed": failed, "skipped": skipped,
                "adopted": adopted}, epoch

    # -- background loop -----------------------------------------------------

    def start(self):
        """Exchange every ``interval_s`` on a daemon thread, starting
        immediately (a cold process converges on the fleet epoch within
        its first round, not after a full interval)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="its-gossip", daemon=True
        )
        self._thread.start()

    def _loop(self):
        while True:
            try:
                self.exchange_once()
            except Exception:
                # One malformed local payload must not kill anti-entropy;
                # per-peer failures are already counted in the round. The
                # counter takes the round lock — a concurrent manual round
                # increments the same ledger (ITS-R001 guard discipline).
                with self._round_lock:
                    self.exchange_failures += 1
            if self._stop.wait(self.interval_s):
                return

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # -- read side -----------------------------------------------------------

    def status(self) -> dict:
        """Flat ``gossip_*`` snapshot for /membership-adjacent dashboards
        and the ``infinistore_gossip_*`` /metrics families (ITS-C006).

        Keys: ``gossip_peers`` (admitted targets), ``gossip_rounds``,
        ``gossip_exchanges`` (successful peer POSTs),
        ``gossip_exchange_failures``, ``gossip_merges_in`` (rounds where
        this process adopted peer knowledge), ``gossip_merges_out``
        (peers that adopted ours), ``gossip_last_epoch_seen``,
        ``gossip_last_round_ms``."""
        with self._lock:
            peers = len(self._targets)
        return {
            "gossip_peers": peers,
            "gossip_rounds": self.rounds,
            "gossip_exchanges": self.exchanges,
            "gossip_exchange_failures": self.exchange_failures,
            "gossip_merges_in": self.merges_in,
            "gossip_merges_out": self.merges_out,
            "gossip_last_epoch_seen": self.last_epoch_seen,
            "gossip_last_round_ms": self.last_round_ms,
        }


# ---------------------------------------------------------------------------
# Metrics history: bounded time series + change-point anomaly journal.
# ---------------------------------------------------------------------------

# Families the history samples by default: the small high-signal set the
# dashboards trend (op tails, occupancy, queue depths, SLO burn, tier and
# prof planes). Bounded on purpose — history is a ring per series, and an
# unselected family is one `startswith` miss per pass, not a leak.
DEFAULT_HISTORY_SELECT: Tuple[str, ...] = (
    "infinistore_op_p50_latency_us",
    "infinistore_op_p99_latency_us",
    "infinistore_pool_usage_ratio",
    "infinistore_kvmap_entries",
    "infinistore_qos_queued",
    "infinistore_dataplane_suspended_ops",
    "infinistore_ring_sq_depth",
    "infinistore_slo_",
    "infinistore_tier_cold_read_p99_us",
    "infinistore_prof_",
    "member_",
)


def parse_metrics_text(text: str) -> Dict[str, float]:
    """Flat ``name{labels} -> value`` map from Prometheus exposition text
    (comments/TYPE lines skipped, exemplar suffixes stripped) — the
    history's input shape. ``tools.top`` keeps its own copy of this
    parse (``_metric_families``) by design: tools/ stays stdlib-only
    with no package import; a format change must touch both."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        line = line.split(" # ", 1)[0]
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


def metrics_http_source(host: str, manage_port: int,
                        timeout_s: float = 2.0) -> Callable[[], Dict[str, float]]:
    """A history source over a manage plane's ``GET /metrics`` (the local
    process's own plane, or any fleet member's)."""
    url = f"http://{host}:{manage_port}/metrics"

    def fetch() -> Dict[str, float]:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return parse_metrics_text(resp.read(4 << 20).decode())

    return fetch


def scraper_source(scraper: "FleetScraper") -> Callable[[], Dict[str, float]]:
    """A history source over the fleet scraper's per-member health rows:
    ``member_ops_per_s{member}`` / ``member_queue_depth{member}`` series,
    so per-member throughput and queue depth trend without a second
    scrape of anyone's manage plane."""

    def fetch() -> Dict[str, float]:
        out: Dict[str, float] = {}
        for m in scraper.status()["members"]:
            out[f'member_ops_per_s{{member="{m["member"]}"}}'] = m["ops_per_s"]
            out[f'member_queue_depth{{member="{m["member"]}"}}'] = float(
                m["queue_depth"]
            )
        return out

    return fetch


class MetricsHistory:
    """Bounded ring of sampled ``/metrics`` families + change-point journal.

    The one-shot ``/metrics`` snapshot answers "what is the p99 NOW"; the
    SLO engine answers "is the budget burning"; neither answers "when did
    it move, and what moved with it". This ring does (docs/observability.md,
    time-series section): every ``interval_s`` it pulls each registered
    source (a callable returning a flat ``name -> value`` map — the local
    manage plane via :func:`metrics_http_source`, the fleet via
    :func:`scraper_source`), keeps the last ``capacity`` points per
    selected series, serves them at ``GET /timeseries``, drives the
    ``tools.top`` sparkline columns, and runs a rolling-window
    change-point detector per series that journals a ``metric_anomaly``
    event on each detected step (edge-triggered with hysteresis — a
    sustained shift is one event, and the journal stamps the active
    trace id like every other kind).

    Detection is deliberately simple and parameter-light: the probe
    window's mean against the preceding baseline window's mean, fired
    when the step exceeds BOTH ``detect_sigma`` baseline standard
    deviations AND ``detect_min_rel`` of the baseline magnitude (the
    relative floor keeps a flat series' zero-sigma from firing on
    float dust, and sigma keeps a noisy series' normal scatter from
    firing on weather). Clock-injectable, nothing sleeps in the math —
    the properties are tested with a fake clock, the bench A/B gates
    exactly-one-on-a-step / zero-on-clean (``timeseries_anomaly``).
    """

    def __init__(self, interval_s: float = 2.0,
                 capacity: int = 256,
                 max_series: int = 128,
                 select: Optional[Tuple[str, ...]] = DEFAULT_HISTORY_SELECT,
                 journal: Optional[EventJournal] = None,
                 clock=time.monotonic,
                 detect_base_n: int = 12,
                 detect_probe_n: int = 4,
                 detect_sigma: float = 4.0,
                 detect_min_rel: float = 0.25):
        if capacity < 2:
            raise ValueError("capacity must be >= 2")
        self.interval_s = interval_s
        self.capacity = capacity
        self.max_series = max_series
        self.select = tuple(select) if select is not None else None
        self.journal = journal if journal is not None else get_journal()
        self._clock = clock
        self.detect_base_n = detect_base_n
        self.detect_probe_n = detect_probe_n
        self.detect_sigma = detect_sigma
        self.detect_min_rel = detect_min_rel
        self._lock = threading.Lock()
        # its: guard[_sources, _series, _armed: _lock]
        self._sources: List[Tuple[str, Callable[[], Dict[str, float]]]] = []
        self._series: Dict[str, deque] = {}  # name -> deque[(t_s, value)]
        self._armed: Dict[str, bool] = {}    # per-series detector edge state
        # its: guard[samples_total, source_failures, dropped_series, anomalies_total, last_pass_ms: _lock]
        self.samples_total = 0
        self.source_failures = 0
        self.dropped_series = 0
        self.anomalies_total = 0
        self.last_pass_ms = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def add_source(self, name: str, fn: Callable[[], Dict[str, float]]):
        """Register a source; ``name`` prefixes its keys (``"name:key"``)
        so two sources exporting the same family cannot collide. The
        empty name is the local process (keys unprefixed)."""
        with self._lock:
            self._sources.append((name, fn))

    def _selected(self, key: str) -> bool:
        if self.select is None:
            return True
        return any(key.startswith(p) for p in self.select)

    # -- one sample pass -----------------------------------------------------

    def _detect_locked(self, name: str, dq: deque) -> Optional[dict]:
        # its: requires[_lock]
        need = self.detect_base_n + self.detect_probe_n
        if len(dq) < need:
            return None
        vals = [v for _, v in list(dq)[-need:]]
        base = vals[: self.detect_base_n]
        probe = vals[self.detect_base_n:]
        base_mean = sum(base) / len(base)
        var = sum((v - base_mean) ** 2 for v in base) / len(base)
        std = var ** 0.5
        probe_mean = sum(probe) / len(probe)
        delta = abs(probe_mean - base_mean)
        threshold = max(
            self.detect_sigma * std,
            self.detect_min_rel * max(abs(base_mean), 1e-9),
        )
        armed = self._armed.get(name, True)
        if armed and delta > threshold:
            self._armed[name] = False
            self.anomalies_total += 1
            return {
                "metric": name,
                "baseline": round(base_mean, 6),
                "current": round(probe_mean, 6),
                "delta": round(probe_mean - base_mean, 6),
                "threshold": round(threshold, 6),
            }
        if not armed and delta < 0.5 * threshold:
            # Hysteresis re-arm: the series settled (at either level) for
            # long enough that the probe/baseline windows agree again.
            self._armed[name] = True
        return None

    def sample_once(self) -> dict:
        """One pass over every source (blocking HTTP for HTTP sources —
        callers keep this off the event loop; the background thread and
        tests drive it). Returns ``{"series", "anomalies"}``; journal
        emits happen OUTSIDE the lock (the ITS-R003 discipline)."""
        t0 = self._clock()
        with self._lock:
            sources = list(self._sources)
        fired: List[dict] = []
        updated = 0
        for name, fn in sources:
            try:
                values = fn()
            except Exception:
                # A dead source costs one failure count per pass, never
                # the pass itself (the scraper discipline).
                with self._lock:
                    self.source_failures += 1
                continue
            now = self._clock()
            with self._lock:
                for key, value in values.items():
                    full = f"{name}:{key}" if name else key
                    if not self._selected(key):
                        continue
                    dq = self._series.get(full)
                    if dq is None:
                        if len(self._series) >= self.max_series:
                            self.dropped_series += 1
                            continue
                        dq = self._series[full] = deque(maxlen=self.capacity)
                    dq.append((now, float(value)))
                    updated += 1
                    anomaly = self._detect_locked(full, dq)
                    if anomaly is not None:
                        fired.append(anomaly)
        for anomaly in fired:
            self.journal.emit("metric_anomaly", **anomaly)
        with self._lock:
            self.samples_total += 1
            self.last_pass_ms = round((self._clock() - t0) * 1e3, 3)
            n_series = len(self._series)
        return {"series": n_series, "updated": updated,
                "anomalies": len(fired)}

    # -- background loop -----------------------------------------------------

    def start(self):
        """Sample every ``interval_s`` on a daemon thread, immediately on
        entry (the scraper discipline: no empty first interval)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="its-metrics-history", daemon=True
        )
        self._thread.start()

    def _loop(self):
        while True:
            try:
                self.sample_once()
            except Exception:
                # Per-source failures are already counted inside the pass;
                # this guards the pass machinery itself.
                with self._lock:
                    self.source_failures += 1
            if self._stop.wait(self.interval_s):
                return

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # -- read side -----------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def points(self, metric: str,
               window_s: Optional[float] = None) -> List[List[float]]:
        """``[[t_s, value], ...]`` oldest-first for one series, clipped to
        the trailing ``window_s`` (monotonic-clock seconds — deltas are
        meaningful, absolutes are process-relative)."""
        now = self._clock()
        with self._lock:
            dq = self._series.get(metric)
            pts = list(dq) if dq is not None else []
        if window_s is not None:
            horizon = now - window_s
            pts = [p for p in pts if p[0] >= horizon]
        return [[round(t, 3), v] for t, v in pts]

    def status(self) -> dict:
        """Flat ``timeseries_*`` snapshot for ``GET /timeseries`` and the
        ``infinistore_timeseries_*`` /metrics families — held in lockstep
        with ``server._timeseries_prometheus_lines`` and
        docs/observability.md by ITS-C008.

        Keys: ``timeseries_series`` (live series), ``timeseries_points``
        (retained points), ``timeseries_samples`` (passes),
        ``timeseries_sources``, ``timeseries_source_failures``,
        ``timeseries_dropped_series`` (series past the cap),
        ``timeseries_anomalies`` (change-points journaled),
        ``timeseries_interval_s``, ``timeseries_capacity``,
        ``timeseries_last_pass_ms``."""
        with self._lock:
            return {
                "timeseries_series": len(self._series),
                "timeseries_points": sum(
                    len(dq) for dq in self._series.values()
                ),
                "timeseries_samples": self.samples_total,
                "timeseries_sources": len(self._sources),
                "timeseries_source_failures": self.source_failures,
                "timeseries_dropped_series": self.dropped_series,
                "timeseries_anomalies": self.anomalies_total,
                "timeseries_interval_s": self.interval_s,
                "timeseries_capacity": self.capacity,
                "timeseries_last_pass_ms": self.last_pass_ms,
            }


# ---------------------------------------------------------------------------
# Cluster trace join.
# ---------------------------------------------------------------------------

def cluster_spans(local_spans: List[dict],
                  member_spans: Dict[str, List[dict]],
                  max_spans: int = 4096) -> List[dict]:
    """Merge the local client recorder's spans with every scraped
    member's spans onto one timeline (everything is CLOCK_MONOTONIC us;
    same-host processes share the timebase — the loopback/bench case —
    and across hosts per-member deltas remain meaningful). Local spans
    are tagged ``member="local"`` unless a member already claimed them;
    output is start-ordered and bounded."""
    merged: List[dict] = []
    for s in local_spans:
        s = dict(s)
        s["attrs"] = {**s.get("attrs", {})}
        s["attrs"].setdefault("member", "local")
        merged.append(s)
    for member_id, spans in member_spans.items():
        for s in spans:
            s = dict(s)
            s["attrs"] = {**s.get("attrs", {})}
            s["attrs"].setdefault("member", member_id)
            merged.append(s)
    merged.sort(key=lambda s: s.get("start_us", 0))
    return merged[-max_spans:]


def cluster_chrome_events(spans: List[dict]) -> List[dict]:
    """Chrome trace events for a cluster-joined span list with ONE
    Perfetto track lane (pid) per member — ``local`` (the client
    recorder) first, then members in first-seen order — plus process_name
    metadata events so Perfetto labels the lanes."""
    lanes: Dict[str, int] = {}
    events: List[dict] = []
    for s in spans:
        member = str(s.get("attrs", {}).get("member", "local"))
        pid = lanes.setdefault(member, len(lanes))
        for e in tracing.chrome_trace_events([s]):
            e["pid"] = pid
            events.append(e)
    for member, pid in lanes.items():
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "ts": 0, "args": {"name": f"member:{member}"},
        })
    return events


# ---------------------------------------------------------------------------
# Process-wide singletons + transition-site helpers.
# ---------------------------------------------------------------------------

_journal = EventJournal()
_slo: Optional[SloEngine] = None
_qos_storm = _StormDetector()
_lock = threading.Lock()


def get_journal() -> EventJournal:
    """The process-wide event journal (always on; events are rare)."""
    return _journal


def emit(kind: str, member: str = "", epoch: int = 0,
         trace_id: Optional[int] = None, **attrs) -> dict:
    """Emit into the process journal (see :meth:`EventJournal.emit`)."""
    return _journal.emit(
        kind, member=member, epoch=epoch, trace_id=trace_id, **attrs
    )


def slo_engine() -> SloEngine:
    """The process-wide SLO engine (default objectives), built lazily so
    importing the package costs nothing."""
    global _slo
    if _slo is None:
        # Audited: O(1) double-checked singleton init — held only for one
        # constructor call, never across IO.
        with _lock:  # its: allow[ITS-L003]
            if _slo is None:
                _slo = SloEngine(journal=_journal)
    return _slo


def configure_slo(engine: Optional[SloEngine]) -> SloEngine:
    """Install a custom engine (tests, bench legs with short windows);
    ``None`` rebuilds the default lazily."""
    global _slo
    _slo = engine
    return slo_engine() if engine is None else engine


def note_qos_aged(n: int = 1, member: str = ""):
    """Transition-site helper for the QoS aging escape: counts toward the
    storm detector and emits ONE ``qos_aging_storm`` event per storm edge
    (docs/qos.md — aged slices are the starvation-proof pressure valve;
    a storm of them means background is systematically starved)."""
    count = _qos_storm.note(n)
    if count:
        _journal.emit("qos_aging_storm", member=member, aged_in_window=count,
                      window_s=_qos_storm.window_s)


def _on_slow_op(span) -> None:
    """Slow-op watchdog hook (registered with tracing at import): every
    watchdog capture lands in the journal with the span's own trace id,
    joining "this op was slow" to the breaker/membership/QoS events
    around it."""
    _journal.emit(
        "slow_op", trace_id=span.trace_id, span=span.name,
        duration_us=span.duration_us, status=span.status or "open",
    )


tracing.set_slow_op_hook(_on_slow_op)


def reset():
    """Test/bench hook: fresh journal contents, default SLO engine, and a
    re-armed storm detector (singleton identities are preserved — code
    that captured ``get_journal()`` keeps a live object)."""
    global _slo, _qos_storm
    _journal.clear()
    _slo = None
    _qos_storm = _StormDetector()
