"""End-to-end op tracing: spans, the flight recorder, and trace export.

One op crosses six subsystems (engine prefetch -> coalescer -> cluster
routing -> striped scheduler -> async bridge -> server reactor), and until
this module the only observability was aggregate counters — the BENCH_r05
loopback gap and the PR-4 450us-e2e-vs-31us-server QoS tail were both
diagnosed with hand-built one-off experiments because nothing attributed
latency to stages. This module makes that attribution first-class:

- A per-op **trace context** (u64 trace id + parent span id) that rides the
  wire as a trailing optional extension after the QoS priority byte
  (``wire.BatchMeta``/``SegBatchMeta`` ``trace_id``/``trace_parent``;
  untagged ops stay byte-identical to the pre-trace format, the same
  scheme PR 4 used for the priority byte).
- **Spans** with stage timestamps: each producer stamps the STAGES vocabulary
  below at the moment the op crosses that boundary. Client stages land
  here; the server reactor stamps ``server_recv``/``first_slice``/
  ``last_slice`` ticks into a parallel native ring exposed through
  ``stats_json()["trace"]`` and joined to client spans by trace id.
- A bounded, lock-cheap **flight recorder** ring per process. With tracing
  off (the default) every hook compiles down to one module-bool check and
  the wire bytes are untouched.
- A **slow-op watchdog**: any span whose wall time exceeds
  ``slow_op_us`` is captured — with its full child-span tree — into a
  separate protected buffer that ring wrap-around cannot evict, and
  counted in ``slow_ops_total`` (exported as
  ``infinistore_trace_slow_ops_total``).
- **Chrome trace-event export** (``chrome_trace_events``): the manage
  plane's ``GET /trace?fmt=chrome`` output loads directly in Perfetto.

The stage vocabulary (the ITS-T checker holds every producer, the /trace
schema and docs/observability.md to this tuple, in lockstep):

- ``enqueue``         request entered the engine (admission t0)
- ``fetch_start``     connector began streaming the hit prefix
- ``coalesce``        submission merged into a batched store call
- ``stripe_claim``    striped scheduler claimed a span for a stripe
- ``submit``          batched op handed to the native client
- ``server_recv``     server reactor finished reading the request [native]
- ``first_slice``     first payload/slice unit of server work     [native]
- ``last_slice``      last payload/slice unit of server work      [native]
- ``completion_ring`` completion drained from the native ring
- ``install``         bytes installed into the engine's paged cache

Clocks: every stamp (Python and native) is CLOCK_MONOTONIC microseconds,
so same-host client and server ticks share a timebase and merge into one
timeline; across hosts only within-process deltas are meaningful.
"""

import contextlib
import contextvars
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

# Canonical stage vocabulary, in pipeline order. Producers may stamp any
# subset (a sync op has no completion_ring; an uncoalesced op no coalesce);
# consumers order by timestamp. The ITS-T checker (tools/analysis/
# trace_stages.py) fails the build when a producer stamps a name outside
# this tuple or when the tuple drifts from docs/observability.md and the
# /trace schema.
STAGES = (
    "enqueue",
    "fetch_start",
    "coalesce",
    "stripe_claim",
    "submit",
    "server_recv",
    "first_slice",
    "last_slice",
    "completion_ring",
    "install",
)

# Stages stamped by the NATIVE server reactor: stats_json()["trace"] tick
# field -> stage name. The /trace endpoint uses this to join server ticks
# into the client span timeline; the ITS-T checker pins the values to
# STAGES.
SERVER_TICK_STAGES = {
    "recv_us": "server_recv",
    "first_slice_us": "first_slice",
    "last_slice_us": "last_slice",
}

_DEFAULT_CAPACITY = 512
_DEFAULT_SLOW_CAPACITY = 64

# The off fast path: one module-global bool guard at every hook site. A
# disabled process pays a dict-free, lock-free attribute read per op.
_ENABLED = False

_ids = itertools.count(1)
_seed = None  # os-random high bits mixed into trace ids (collision guard)


def _now_us() -> int:
    """CLOCK_MONOTONIC microseconds — the same clock the native reactor
    stamps (server.cpp now_us), so same-host ticks merge directly."""
    return time.monotonic_ns() // 1000


def _new_id() -> int:
    """Process-unique, never-zero u64 (zero = 'untraced' on the wire):
    os-random high bits + a process-local counter."""
    global _seed
    if _seed is None:
        _seed = int.from_bytes(os.urandom(4), "little") or 1
    return ((_seed << 24) ^ next(_ids)) & 0xFFFFFFFFFFFFFFFF or 1


class Span:
    """One traced operation: a bag of (stage, t_us) stamps plus identity.

    Spans are cheap and lock-free to stamp (list append under the GIL);
    they are published to the flight recorder only at :meth:`finish`.
    ``parent_id`` links child spans (striped chunk ops, coalesced group
    members) into the tree the slow-op watchdog captures whole.
    """

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "t0_us", "t1_us",
        "stages", "status", "attrs",
    )

    def __init__(self, name: str, trace_id: Optional[int] = None,
                 parent_id: int = 0):
        self.name = name
        self.trace_id = trace_id if trace_id else _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.t0_us = _now_us()
        self.t1_us = 0
        self.stages: List = []  # [(stage_name, t_us), ...] append-only
        self.status = ""  # "" = open; "ok" / "error:<Type>" once finished
        self.attrs: Dict = {}

    def stage(self, name: str):
        """Stamp one stage boundary NOW. Repeats are legal (a striped op
        submits many chunks); consumers use the first occurrence for
        breakdowns and keep the rest for per-chunk visibility."""
        self.stages.append((name, _now_us()))

    def annotate(self, **attrs):
        """Attach routing/context attributes (member index, stripe, bytes)."""
        self.attrs.update(attrs)

    @property
    def duration_us(self) -> int:
        end = self.t1_us or _now_us()
        return max(0, end - self.t0_us)

    def stage_ts(self, name: str) -> Optional[int]:
        """First timestamp recorded for ``name`` (None when never stamped)."""
        for stage, ts in self.stages:
            if stage == name:
                return ts
        return None

    def finish(self, status: str = "ok"):
        """Close the span and publish it to the flight recorder (idempotent:
        only the first finish records)."""
        if self.status:
            return
        self.status = status
        self.t1_us = _now_us()
        rec = _recorder
        if rec is not None:
            rec.record(self)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_us": self.t0_us,
            "end_us": self.t1_us,
            "duration_us": self.duration_us,
            "status": self.status or "open",
            "stages": [[s, t] for s, t in self.stages],
            "attrs": dict(self.attrs),
        }


class FlightRecorder:
    """Bounded ring of finished spans + a protected slow-op buffer.

    The hot path (``record``) is one lock-guarded index bump and slot
    store — no allocation, no scan. The slow-op watchdog runs inside the
    same record call: a span slower than ``slow_op_us`` is copied (with
    every already-recorded span of its trace — the full tree) into
    ``slow``, a smaller buffer ring wrap-around cannot touch, and
    ``slow_ops_total`` increments (``infinistore_trace_slow_ops_total``).
    """

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 slow_op_us: int = 0,
                 slow_capacity: int = _DEFAULT_SLOW_CAPACITY):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.slow_op_us = slow_op_us  # 0 = watchdog off
        self.slow_capacity = max(1, slow_capacity)
        # its: cross-thread  (spans finish on loop, engine and worker
        # threads alike; the manage plane snapshots)
        # its: guard[_slots, _next, _slow: _lock]
        self._slots: List[Optional[Span]] = [None] * capacity
        self._next = 0  # monotone: total spans ever recorded
        self._slow: List[dict] = []
        self._lock = threading.Lock()
        # its: guard[recorded, dropped, slow_ops_total: _lock!w]
        self.recorded = 0
        self.dropped = 0  # spans a full ring overwrote
        self.slow_ops_total = 0

    def record(self, span: Span):
        slow = False
        with self._lock:
            idx = self._next % self.capacity
            if self._next >= self.capacity:
                self.dropped += 1
            self._slots[idx] = span
            self._next += 1
            self.recorded += 1
            if self.slow_op_us and span.duration_us >= self.slow_op_us:
                self._capture_slow_locked(span)
                slow = True
        if slow:
            # Outside the (non-reentrant) ring lock: a hook that itself
            # records or finishes a span must not deadlock the recorder.
            hook = _slow_op_hook
            if hook is not None:
                try:
                    hook(span)
                except Exception:
                    # A listener (the telemetry journal) must never be able
                    # to fail the recording hot path.
                    pass

    def _capture_slow_locked(self, span: Span):  # its: requires[_lock]
        self.slow_ops_total += 1
        tree = [s.as_dict() for s in self._slots
                if s is not None and s.trace_id == span.trace_id]
        self._slow.append({
            "trace_id": span.trace_id,
            "root": span.as_dict(),
            "spans": tree,
        })
        if len(self._slow) > self.slow_capacity:
            del self._slow[: len(self._slow) - self.slow_capacity]

    def snapshot(self) -> List[dict]:
        """Recorded spans, oldest first (at most ``capacity``)."""
        with self._lock:
            start = max(0, self._next - self.capacity)
            return [
                self._slots[i % self.capacity].as_dict()
                for i in range(start, self._next)
                if self._slots[i % self.capacity] is not None
            ]

    def slow_snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._slow)

    def clear(self):
        with self._lock:
            self._slots = [None] * self.capacity
            self._next = 0
            self._slow = []


_recorder: Optional[FlightRecorder] = None
_current: contextvars.ContextVar = contextvars.ContextVar(
    "its_trace_span", default=None
)

# Slow-op listener (telemetry.py registers the event journal here at
# import). A plain module slot, not a list: exactly one fleet-telemetry
# plane per process, and tracing must not import telemetry (telemetry
# imports tracing).
_slow_op_hook = None


def set_slow_op_hook(cb) -> None:
    """Register ``cb(span)`` to run on every slow-op watchdog capture
    (``None`` unregisters). Exceptions from the hook are swallowed — it
    observes the recorder, it cannot fail it."""
    global _slow_op_hook
    _slow_op_hook = cb


# Span-bind listener (profiling.py registers the sampling profiler's
# thread->span map feed here). Same single-slot pattern as the slow-op
# hook and for the same reason: profiling imports tracing, not the
# reverse. Called with the NEW active span (or None) after every bind/
# unbind on the calling thread; with no profiler the cost is one None
# check per bind — and binds only happen on traced ops.
_bind_hook = None


def set_bind_hook(cb) -> None:
    """Register ``cb(span_or_none)`` to observe active-span changes on
    whatever thread performs them (``None`` unregisters). Exceptions are
    swallowed — an observer cannot fail the traced op."""
    global _bind_hook
    _bind_hook = cb


def _notify_bind():
    hook = _bind_hook
    if hook is not None:
        try:
            hook(_current.get())
        except Exception:
            pass


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None,
              slow_op_us: Optional[int] = None) -> Optional[FlightRecorder]:
    """(Re)configure process-wide tracing; returns the active recorder.

    A FRESH :class:`FlightRecorder` is built whenever ``capacity`` or
    ``slow_op_us`` is given (even while disabled — the sizing takes
    effect, it just records nothing until enabled), or when tracing is
    enabled with no recorder yet. Toggling ``enabled`` ALONE keeps the
    existing recorder and its contents: ``enabled=False`` preserves it
    for post-mortem reads (``GET /trace`` after the incident), and a
    bare ``enabled=True`` resumes recording into it. ``slow_op_us=0``
    disables the watchdog.
    """
    global _ENABLED, _recorder
    if enabled is not None:
        _ENABLED = bool(enabled)
    if (
        capacity is not None or slow_op_us is not None
        or (_ENABLED and _recorder is None)
    ):
        cap = capacity if capacity is not None else (
            _recorder.capacity if _recorder else _DEFAULT_CAPACITY
        )
        slow = slow_op_us if slow_op_us is not None else (
            _recorder.slow_op_us if _recorder else 0
        )
        _recorder = FlightRecorder(capacity=cap, slow_op_us=slow)
    return _recorder


def enabled() -> bool:
    """The one-instruction guard every hook site checks first."""
    return _ENABLED


# Operator opt-in without code changes (e.g. to light up GET /trace on a
# running server deployment): INFINISTORE_TPU_TRACE=1 enables at import,
# INFINISTORE_TPU_TRACE_SLOW_US arms the watchdog threshold.
if os.environ.get("INFINISTORE_TPU_TRACE", "") not in ("", "0"):
    configure(
        enabled=True,
        slow_op_us=int(os.environ.get("INFINISTORE_TPU_TRACE_SLOW_US", "0") or 0),
    )


def recorder() -> Optional[FlightRecorder]:
    return _recorder


def active_span() -> Optional[Span]:
    """The span bound to the current (task) context, or None. Costs one
    bool check when tracing is off."""
    if not _ENABLED:
        return None
    return _current.get()


def start_span(name: str, parent: Optional[Span] = None) -> Optional[Span]:
    """New span (child of ``parent`` when given, else of the active span);
    None when tracing is off. The caller owns finish()."""
    if not _ENABLED:
        return None
    if parent is None:
        parent = _current.get()
    if parent is not None:
        return Span(name, trace_id=parent.trace_id, parent_id=parent.span_id)
    return Span(name)


@contextlib.contextmanager
def use_span(span: Optional[Span]):
    """Bind ``span`` as the context's active span for the with-body (no-op
    for None, so call sites stay unconditional)."""
    if span is None:
        yield None
        return
    token = _current.set(span)
    _notify_bind()
    try:
        yield span
    finally:
        _current.reset(token)
        _notify_bind()


@contextlib.contextmanager
def override_span(span: Optional[Span]):
    """Like :func:`use_span`, but ``None`` CLEARS any inherited binding for
    the with-body instead of no-op'ing. For code issuing work on behalf of
    several submitters (the fetch coalescer): a task inherits its
    scheduler's contextvars, so an untraced merged op would otherwise ride
    — and stamp — an unrelated submitter's span."""
    if not _ENABLED:
        yield span
        return
    token = _current.set(span)
    _notify_bind()
    try:
        yield span
    finally:
        _current.reset(token)
        _notify_bind()


def bind_span(span: Optional[Span]):
    """Non-contextmanager form of :func:`use_span` for call sites whose
    span outlives one lexical block (e.g. an engine request coroutine):
    returns the reset token to hand back to :func:`unbind_span` (None for
    an untraced op)."""
    if span is None:
        return None
    token = _current.set(span)
    _notify_bind()
    return token


def unbind_span(token):
    if token is not None:
        _current.reset(token)
        _notify_bind()


@contextlib.contextmanager
def trace_op(name: str, stage: Optional[str] = None):
    """Span-per-operation context manager: opens a span (child of any
    active one), binds it, optionally stamps ``stage`` on entry, and
    finishes it with ``ok`` or ``error:<Type>`` — so an op that dies on a
    tripped circuit breaker still closes its span with an error status.
    Yields None (and costs one bool check) when tracing is off."""
    span = start_span(name)
    if span is None:
        yield None
        return
    if stage is not None:
        span.stage(stage)
    token = _current.set(span)
    _notify_bind()
    try:
        yield span
    except BaseException as e:
        span.finish(status=f"error:{type(e).__name__}")
        raise
    finally:
        _current.reset(token)
        _notify_bind()
        span.finish()


def wire_ids(span: Optional[Span]):
    """(trace_id, span_id) to put on the wire for this op — (0, 0) when
    untraced, which encodes as ZERO extra wire bytes."""
    if span is None:
        return 0, 0
    return span.trace_id, span.span_id


# ---------------------------------------------------------------------------
# Export: /trace JSON + Chrome trace-event format (Perfetto-loadable).
# ---------------------------------------------------------------------------

def server_tick_spans(server_trace: dict) -> List[dict]:
    """Convert the native reactor's trace ring (``stats_json()["trace"]``)
    into span dicts on the shared stage vocabulary, joinable to client
    spans by trace id. Every tick field is consumed by name here — the
    counters checker (ITS-C001) holds the native ring's key vocabulary to
    this function, so a tick the exporter cannot see fails the build."""
    out = []
    server_trace = server_trace or {}
    entries = server_trace.get("entries", [])
    for e in entries:
        stages = []
        if e.get("recv_us"):
            stages.append([SERVER_TICK_STAGES["recv_us"], e["recv_us"]])
        if e.get("first_slice_us"):
            stages.append(
                [SERVER_TICK_STAGES["first_slice_us"], e["first_slice_us"]]
            )
        if e.get("last_slice_us"):
            stages.append(
                [SERVER_TICK_STAGES["last_slice_us"], e["last_slice_us"]]
            )
        out.append({
            "name": f"server:{e.get('op', '?')}",
            "trace_id": e.get("trace_id", 0),
            "span_id": 0,
            "parent_id": e.get("parent_id", 0),
            "start_us": e.get("recv_us", 0),
            "end_us": e.get("done_us", 0),
            "duration_us": max(
                0, e.get("done_us", 0) - e.get("recv_us", 0)
            ),
            "status": "ok" if e.get("ok", 1) else "error",
            "stages": stages,
            "attrs": {"bytes": e.get("bytes", 0), "prio": e.get("prio", 0),
                      "side": "server"},
        })
    return out


def chrome_trace_events(spans: List[dict]) -> List[dict]:
    """Chrome trace-event objects (the ``traceEvents`` array) for a list of
    span dicts: one complete ("X") event per span on a per-trace track,
    plus an instant ("i") event per stage stamp. ``chrome://tracing`` and
    Perfetto load ``{"traceEvents": [...], "displayTimeUnit": "ns"}``
    directly."""
    events = []
    for s in spans:
        tid = s.get("trace_id", 0) % 100000
        pid = 1 if s.get("attrs", {}).get("side") == "server" else 0
        end = s.get("end_us") or s.get("start_us", 0)
        events.append({
            "name": s.get("name", "op"),
            "cat": "infinistore",
            "ph": "X",
            "ts": s.get("start_us", 0),
            "dur": max(0, end - s.get("start_us", 0)),
            "pid": pid,
            "tid": tid,
            "args": {
                "trace_id": f"{s.get('trace_id', 0):#x}",
                "span_id": f"{s.get('span_id', 0):#x}",
                "status": s.get("status", ""),
                **{k: v for k, v in s.get("attrs", {}).items()},
            },
        })
        for stage, ts in s.get("stages", []):
            events.append({
                "name": stage,
                "cat": "stage",
                "ph": "i",
                "s": "t",
                "ts": ts,
                "pid": pid,
                "tid": tid,
                "args": {"trace_id": f"{s.get('trace_id', 0):#x}"},
            })
    return events


def stage_breakdown(spans: List[dict]) -> Dict[str, float]:
    """Fraction of wall time between consecutive present stages, averaged
    over spans, keyed ``stage_a->stage_b`` in canonical STAGES order plus
    a ``total_us`` mean. Fractions sum to ~1.0 of the first->last stage
    wall time by construction — the bench's per-stage receipt."""
    order = {name: i for i, name in enumerate(STAGES)}
    sums: Dict[str, float] = {}
    totals = []
    for s in spans:
        first: Dict[str, int] = {}
        for stage, ts in s.get("stages", []):
            if stage in order and stage not in first:
                first[stage] = ts
        present = sorted(first, key=lambda n: first[n])
        if len(present) < 2:
            continue
        span_total = first[present[-1]] - first[present[0]]
        if span_total <= 0:
            continue
        totals.append(span_total)
        for a, b in zip(present, present[1:]):
            sums[f"{a}->{b}"] = sums.get(f"{a}->{b}", 0.0) + (
                (first[b] - first[a]) / span_total
            )
    n = len(totals)
    if n == 0:
        return {}
    out = {k: v / n for k, v in sums.items()}
    out["total_us"] = sum(totals) / n
    return out
