"""Engine-facing KV-cache connector: the LMCache-style glue layer.

The reference integrates with vLLM "through LMCache" (reference README.md:22):
the engine never speaks the store protocol directly — a connector hashes token
prefixes into chain keys, asks the store how much of a prompt is already
cached (`get_match_last_index`, reference src/infinistore.cpp:786-798), and
streams paged-KV blocks layer by layer. This module is that connector for
JAX/TPU engines: it binds a paged cache spec + host staging pool + store
connection to a model id and exposes lookup / save / load in engine terms
(token ids and block ids), with the chain-hash key scheme that makes
cross-request prefix reuse work (reference docs/source/design.rst:50).

Key scheme: ``{model}/L{layer}/{k|v}/{chain_hash_i}`` where ``chain_hash_i``
is a rolling SHA-256 over token blocks [0..i]. A block's key therefore commits
to the *entire prefix*, so two prompts share keys exactly for their common
block-aligned prefix — and the store's binary-search prefix match applies.
"""

import asyncio
import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import tracing, wire
from .lib import (
    InfiniStoreColdTier,
    InfiniStoreKeyNotFound,
    InfiniStoreNoMatch,
    InfiniStoreResourcePressure,
)
from .tiering import note_demotion_hit as tiering_note_demotion_hit
from .tpu.layerwise import (
    LayerwiseKVReader,
    LayerwiseKVWriter,
    LayerwisePrefetch,
    PartialReadError,
)
from .tpu.paged import PagedKVCacheSpec
from .tpu.staging import HostStagingPool, StagingPoolExhausted  # noqa: F401 - re-export


def token_chain_hashes(token_ids: Sequence[int], block_tokens: int) -> List[str]:
    """Rolling prefix hash per *complete* token block.

    hash_i covers tokens [0, (i+1) * block_tokens); an incomplete tail block
    is excluded (it cannot be reused — its key would never match another
    request's complete block).
    """
    n_full = len(token_ids) // block_tokens
    hashes = []
    h = hashlib.sha256()
    for i in range(n_full):
        chunk = np.asarray(
            token_ids[i * block_tokens : (i + 1) * block_tokens], dtype=np.int64
        )
        h.update(chunk.tobytes())
        hashes.append(h.copy().hexdigest()[:32])
    return hashes


class _ChainHashCache:
    """Incremental chain-hash cache for repeated/extended token prefixes.

    Chain hashes commit to the whole prefix, so an unchanged prefix yields
    byte-identical hashes call after call — yet every connector entry point
    (lookup, load, save, start_fetch, per-layer saves) re-ran one sha256
    update PER BLOCK per call. This caches the last prompt's full-block
    tokens, its chain list, and the live sha256 state after the final full
    block:

    - same prompt again        -> one array compare, zero hashing
    - the cached prompt's own  -> a slice of the cached chains (hash_i only
      prefix (fewer blocks)       depends on tokens [0, (i+1)*block); the
                                  cache keeps the LONGER chain)
    - extended prompt          -> hash only the new tail blocks (decode
                                  steps growing a prompt block by block pay
                                  O(new), not O(total))
    - anything else            -> full recompute, cache replaced

    One entry only, held as ONE tuple read once and swapped atomically
    (the GIL makes the swap safe; sync lookups may run from concurrent
    threads — same discipline as InfinityConnection's match-blob cache):
    admission churn alternating between two prompt families costs a
    recompute, never a wrong hash."""

    __slots__ = ("_state",)

    def __init__(self):
        # (block_tokens, full-block tokens ndarray, chain hashes, sha256
        # state after the last cached full block) — or None before first use.
        self._state: Optional[tuple] = None

    def hashes(self, token_ids: Sequence[int], block_tokens: int) -> List[str]:
        n_full = len(token_ids) // block_tokens
        if n_full == 0:
            return []
        # copy=True matters: for ndarray inputs asarray would keep a VIEW of
        # the caller's buffer, and an engine reusing that buffer for the next
        # prompt would mutate our cached tokens into falsely matching it —
        # returning the OLD prompt's hashes (another request's KV keys).
        toks = np.array(token_ids[: n_full * block_tokens], dtype=np.int64, copy=True)
        state = self._state  # one read: threads race the swap, never a tear
        if state is not None and state[0] == block_tokens:
            _, c_toks, c_hashes, c_h = state
            if toks.size <= c_toks.size and np.array_equal(
                toks, c_toks[: toks.size]
            ):
                # Repeat or prefix of the cached prompt: pure cache read
                # (keep the longer entry — serving its prefixes is free).
                return c_hashes[:n_full]
            if toks.size > c_toks.size and np.array_equal(
                toks[: c_toks.size], c_toks
            ):
                # Extension: hash only the new tail blocks.
                h = c_h.copy()
                hashes = list(c_hashes)
                for i in range(len(hashes), n_full):
                    h.update(toks[i * block_tokens : (i + 1) * block_tokens].tobytes())
                    hashes.append(h.copy().hexdigest()[:32])
                self._state = (block_tokens, toks, hashes, h)  # atomic swap
                return list(hashes)
        h = hashlib.sha256()
        hashes = []
        for i in range(n_full):
            h.update(toks[i * block_tokens : (i + 1) * block_tokens].tobytes())
            hashes.append(h.copy().hexdigest()[:32])
        self._state = (block_tokens, toks, hashes, h)  # atomic swap
        return list(hashes)


class FetchCoalescer:
    """Merge store reads issued in the same event-loop tick into ONE
    batched ``read_cache_async`` call.

    A wave of concurrent admissions starts one prefetch each; without
    coalescing, every layer of every request is its own store round trip.
    Batched, the wave's reads ride a single call — which a
    ``StripedConnection`` then splits across its connection stripes, so a
    burst of admissions shares the stripes instead of queueing serially.

    All submitters must target the same base pointer (one staging pool)
    and block size; the coalescer only merges, it never copies.

    Merges are SIZED to the connection's fan-out: a striped connection
    reports ``preferred_fanout_blocks()`` (every stripe's maximum per-trip
    pull — more blocks in one call adds no parallelism), and a tick's
    submissions are packed into merged calls of at most that many blocks,
    issued concurrently. This keeps a mega-wave's failure isolation at
    group granularity (one evicted key re-splits its group, not the whole
    wave) without giving up the per-call amortization merging exists for.
    Unstriped connections report no hint and keep the single-merge
    behavior."""

    def __init__(self, conn, block_size: int, base_ptr: int,
                 max_merge_blocks: Optional[int] = None):
        self.conn = conn
        self.block_size = block_size
        self.base_ptr = base_ptr
        if max_merge_blocks is None:
            hint = getattr(conn, "preferred_fanout_blocks", None)
            max_merge_blocks = hint() if callable(hint) else 0
        self.max_merge_blocks = max_merge_blocks or 0  # 0 = unbounded
        self._pending: list = []
        self._flush_scheduled = False
        # Strong refs: the loop holds only weak refs to tasks (same
        # discipline as engine.WaveDecoder).
        self._flush_tasks: set = set()
        self.calls = 0  # batched store calls issued
        self.submissions = 0  # logical submits merged into them
        self.max_batch = 0
        self.ring_windows = 0  # flushes that opened a ring batch window

    def submit(self, blocks, priority: int = 0) -> "asyncio.Future":
        """Queue one logical read (list of (key, offset-from-base) pairs);
        returns a future resolving when those bytes are staged.
        ``priority``: QoS class (wire.PRIORITY_*) — submissions merge only
        with same-class peers, so a BACKGROUND speculative prefetch never
        drags a FOREGROUND admission fetch into its service class.

        Tracing: the submitter's active span is captured HERE — the flush
        task inherits the contextvars of whichever submitter SCHEDULED it,
        not of each merged peer — and stamped ``coalesce`` when its merged
        batched call issues (docs/observability.md)."""
        fut = asyncio.get_running_loop().create_future()
        self._pending.append((blocks, fut, priority, tracing.active_span()))
        self.submissions += 1
        if not self._flush_scheduled:
            self._flush_scheduled = True
            task = asyncio.ensure_future(self._flush())
            self._flush_tasks.add(task)
            task.add_done_callback(self._flush_tasks.discard)
        return fut

    def _group(self, batch):
        """Pack this tick's submissions into merged-call groups of at most
        ``max_merge_blocks`` blocks (a single oversized submission still
        rides alone — the data plane chunks it internally), partitioned by
        QoS class first so each merged call carries one honest tag."""
        by_class: dict = {}
        for blocks, fut, priority, span in batch:
            by_class.setdefault(priority, []).append((blocks, fut, span))
        groups = []
        for priority, items in by_class.items():
            if not self.max_merge_blocks:
                groups.append((priority, items))
                continue
            cur, cur_blocks = [], 0
            for blocks, fut, span in items:
                if cur and cur_blocks + len(blocks) > self.max_merge_blocks:
                    groups.append((priority, cur))
                    cur, cur_blocks = [], 0
                cur.append((blocks, fut, span))
                cur_blocks += len(blocks)
            if cur:
                groups.append((priority, cur))
        return groups

    async def _flush(self):
        # One yield: everything enqueued this tick joins the batch.
        await asyncio.sleep(0)
        batch, self._pending = self._pending, []
        self._flush_scheduled = False
        if not batch:
            return
        # Eagerly open this tick's ring batch window (no-op off-ring or on
        # a pre-ring connection stand-in): the gathered merged calls — and
        # any per-stripe grandchild tasks a StripedConnection spawns before
        # the window's call_soon flush runs — then publish their ring posts
        # as ONE multi-op batch slot instead of one slot + doorbell each
        # (docs/descriptor_ring.md, batch-slot section).
        window = getattr(self.conn, "ring_batch_window", None)
        if callable(window):
            window()
            self.ring_windows += 1
        await asyncio.gather(*(self._issue(g, p) for p, g in self._group(batch)))

    async def _issue(self, batch, priority: int = 0):
        self.calls += 1
        self.max_batch = max(self.max_batch, len(batch))
        merged = [b for blocks, _, _ in batch for b in blocks]
        pri_kw = wire.qos_kwargs(self.conn, priority)
        # Tracing: every merged submission stamps `coalesce` now; the
        # merged wire op rides the FIRST traced submitter's context (one
        # batched call carries one trace id — siblings still see their
        # merge moment and group size). override_span, not use_span: this
        # flush task INHERITS the scheduling submitter's contextvars, so a
        # fully-untraced group must clear that inherited span or its wire
        # op (and stamps) would be misattributed to an unrelated request.
        lead_span = None
        for _, _, span in batch:
            if span is not None:
                span.stage("coalesce")
                span.annotate(coalesced_group=len(batch))
                if lead_span is None:
                    lead_span = span
        try:
            with tracing.override_span(lead_span):
                await self.conn.read_cache_async(
                    merged, self.block_size, self.base_ptr, **pri_kw
                )
        except Exception as e:
            # Per-submission retry exists to isolate ONE evicted/pressured
            # key from its group-mates. A transport error is different: the
            # whole connection is sick, and re-driving N submissions into it
            # would burn N more timeouts against a dead store — fail the
            # group fast instead (the store's own failover/breaker layers
            # decide what happens next).
            retryable = isinstance(
                e, (InfiniStoreKeyNotFound, InfiniStoreResourcePressure)
            )
            if len(batch) == 1 or not retryable:
                for blocks, fut, _ in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return
            for blocks, fut, span in batch:
                if fut.done():
                    continue
                self.calls += 1
                try:
                    with tracing.override_span(span):
                        await self.conn.read_cache_async(
                            blocks, self.block_size, self.base_ptr, **pri_kw
                        )
                except Exception as e2:
                    fut.set_exception(e2)
                else:
                    fut.set_result(None)
            return
        for _, fut, _ in batch:
            if not fut.done():
                fut.set_result(None)


class KVConnector:
    """Bind one model's paged KV cache to a store connection.

    ``QOS_AWARE``: this connector accepts the two-class priority kwarg on
    ``start_fetch`` (adapters gate forwarding on the attribute so pre-QoS
    connector stand-ins keep working — see docs/qos.md).

    The engine calls, per request:
      - ``lookup(tokens)`` -> how many leading blocks are already cached
      - ``load(tokens, caches, block_ids)`` -> scatter those blocks into the
        engine's paged cache (skipping recompute of the shared prefix)
      - ``save(tokens, caches, block_ids)`` -> stream the request's blocks
        out, layer by layer, overlapping D2H with the network
    """

    QOS_AWARE = True

    def __init__(
        self,
        conn,
        spec: PagedKVCacheSpec,
        model_id: str,
        max_blocks: int,
        pool: Optional[HostStagingPool] = None,
        ici=None,
    ):
        """``ici``: an optional ``IciBlockTransfer`` bound to the SPMD mesh
        this engine runs in. When set, ``handoff`` moves blocks HBM->HBM over
        the interconnect; without it (or across meshes) the same call
        degrades to the DCN store path (SURVEY §7 hard part 4). ``conn`` may
        be None for a pure-ICI connector (no store in the loop)."""
        self.conn = conn
        self.spec = spec
        self.model_id = model_id
        self.max_blocks = max_blocks
        self.ici = ici
        if conn is None:
            # Pure-ICI connector: no store data plane, so don't allocate the
            # (potentially tens of MB) host staging pool it would need.
            self.pool = pool
            self._writer = self._reader = None
        else:
            if pool is None:
                # 6 read-staging regions (K+V each): deep enough that network
                # fetches and H2D uploads overlap several layers (layerwise.py
                # _LayerRegions adapts the pipeline depth to this size).
                pool = HostStagingPool(
                    12 * max_blocks * spec.block_nbytes, spec.block_nbytes, conn=conn
                )
            self.pool = pool
            self._writer = LayerwiseKVWriter(conn, pool, spec, max_blocks)
            self._reader = LayerwiseKVReader(conn, pool, spec, max_blocks)
        # Two-phase admission path (start_fetch): its own staging pool —
        # the reader's ``_LayerRegions`` owns ``pool``'s layout outright, so
        # speculative prefetches reserve from a separate arena. Lazy: only
        # engines on the pipelined path pay for it.
        self._prefetch_pool: Optional[HostStagingPool] = None
        self._coalescer: Optional[FetchCoalescer] = None
        # Chain-hash + sentinel-key caches: admission re-derives the same
        # prefix's keys on every lookup/load/save (satellite of the adaptive
        # data-plane PR; BENCH_r05 put the 256-chain lookup at 26.1us with
        # the hashing/keying on top of it).
        self._chain_cache = _ChainHashCache()
        self._keys0_cache: Optional[Tuple[List[str], List[str]]] = None

    def _require_store(self, what: str):
        if self.conn is None:
            raise ValueError(
                f"{what} needs a store connection; this connector was built "
                "conn=None (pure-ICI)"
            )

    # -- key scheme ----------------------------------------------------------

    def block_key(self, layer: int, kind: str, chain_hash: str) -> str:
        """Store key for one block: ``{model}/L{layer}/{k|v}/{chain_hash}``."""
        return f"{self.model_id}/L{layer}/{kind}/{chain_hash}"

    def _key_fn(self, chains: List[str]):
        def key_fn(layer: int, kind: str, block: int) -> str:
            return self.block_key(layer, kind, chains[block])

        return key_fn

    def _chains(self, token_ids: Sequence[int]) -> List[str]:
        """Chain hashes for this prompt's complete blocks, served from the
        incremental cache (repeat prefixes are an array compare; extensions
        hash only their tail)."""
        return self._chain_cache.hashes(token_ids, self.spec.block_tokens)

    def _sentinel_keys(self, chains: List[str]) -> List[str]:
        """Layer-0 K keys for a chain (the whole-block presence sentinels
        lookups send). Cached: because chain hash i commits to the entire
        prefix, a match on length + final hash proves the whole key list is
        the cached one — repeated admissions of a hot prefix skip N string
        formats per call, and a shorter chain is served as a slice of a
        cached longer one."""
        cached = self._keys0_cache
        n = len(chains)
        if cached is not None:
            c_chains, c_keys = cached
            if len(c_chains) >= n and c_chains[n - 1] == chains[-1]:
                return c_keys[:n]
        keys = [self.block_key(0, "k", c) for c in chains]
        self._keys0_cache = (list(chains), keys)
        return keys

    def manifest(self, token_ids, n_blocks: Optional[int] = None):
        """Every store key this connector would hold for the prompt's first
        ``n_blocks`` complete blocks (default: all), as size-grouped
        ``[(block_nbytes, [key, ...])]`` — the raw-byte inventory the
        membership resharder migrates between members without knowing the
        key scheme (docs/membership.md). Sentinel ordering: the layer-0 K
        key of each block (what ``lookup`` probes) is LAST in its group, so
        a batched copy that dies mid-stream never publishes a sentinel for
        an incompletely copied block."""
        chains = self._chains(token_ids)
        if n_blocks is not None:
            chains = chains[:n_blocks]
        keys = [
            self.block_key(layer, kind, c)
            for layer in range(self.spec.num_layers)
            for kind in ("k", "v")
            for c in chains
            if (layer, kind) != (0, "k")
        ] + [self.block_key(0, "k", c) for c in chains]
        return [(self.spec.block_nbytes, keys)] if keys else []

    # -- engine surface ------------------------------------------------------

    def lookup(self, token_ids: Sequence[int]) -> int:
        """Number of leading blocks of this prompt already in the store.

        One control round-trip: the layer-0 K keys stand in for the whole
        block (the writer commits layer 0 last, so a present sentinel means
        every layer is present), and the store's binary-search longest-prefix
        match does the rest.

        Only a semantic no-match maps to 0. A dead store, a timeout, or a
        protocol error raises — the engine must see the difference between
        "not cached" and "store unreachable", or it silently recomputes
        forever (the reference likewise surfaces transport errors as their
        own exceptions, reference lib.py:575-577).
        """
        self._require_store("lookup")
        return self._lookup_chains(self._chains(token_ids))

    def _lookup_chains(self, chains: List[str]) -> int:
        if not chains:
            return 0
        keys = self._sentinel_keys(chains)
        try:
            # Audited: the blocking probe RTT. Every async caller hops it
            # through an executor (load()'s to_thread, start_fetch_async's
            # known_hit handoff); the remaining inline path is sync
            # lookup()/start_fetch(), whose docstrings own the cost.
            return self.conn.get_match_last_index(keys) + 1  # its: allow[ITS-L001]
        except InfiniStoreNoMatch:
            return 0

    async def save(
        self, token_ids, caches, block_ids: np.ndarray, first_block: int = 0,
        priority: int = wire.PRIORITY_BACKGROUND,
    ) -> int:
        """Stream the request's KV blocks to the store. ``block_ids[i]`` is
        the engine's physical block holding logical block ``first_block + i``
        of this prompt. Returns blocks written (K+V across layers).

        Saves are BACKGROUND class by default (docs/qos.md): a prefill save
        is never decode-blocking, so its store puts yield to concurrent
        foreground reads in every queue they cross. Pass
        ``priority=wire.PRIORITY_FOREGROUND`` to opt a save out (e.g. a
        handoff the consumer is already waiting on).

        ``first_block`` serves sharded producers: under sequence-parallel
        prefill (models/long_context.py) each host holds only its chunk's
        blocks — it passes the FULL token list (chain hashes commit to the
        whole prefix) but saves just its logical span. The spans compose:
        once every shard saved, a consumer's lookup sees the whole prefix."""
        self._require_store("save")
        chains = self._chains(token_ids)
        if first_block < 0 or first_block > len(chains):
            raise ValueError(
                f"first_block={first_block} outside the prompt's "
                f"{len(chains)} complete blocks"
            )
        chains = chains[first_block:]
        n = min(len(chains), len(block_ids))
        if n == 0:
            return 0
        return await self._writer.write(
            caches, np.asarray(block_ids[:n]), self._key_fn(chains),
            priority=priority,
        )

    async def load(
        self, token_ids, caches, block_ids: np.ndarray, first_block: int = 0,
        on_layer=None,
    ):
        """Fetch this prompt's cached prefix into the engine's paged cache.

        Fetches up to ``lookup(tokens) - first_block`` blocks (capped by
        len(block_ids)) and scatters them; returns (updated caches,
        blocks_loaded). ``first_block`` skips a prefix the engine already
        holds (its own prefix cache / computed tokens): ``block_ids[i]``
        then receives logical block ``first_block + i`` — symmetric with
        ``save``'s ``first_block``.

        DONATION: the input ``caches`` are consumed (scatter_blocks donates
        the cache buffer on TPU so the update is in-place in HBM). Use the
        returned caches; do not touch the inputs again — on a real chip they
        are deleted buffers after this call.

        ``on_layer(layer, (k, v))``: optional per-layer progress hook
        (layers complete in order — see LayerwiseKVReader.read), the seam
        the vLLM-v1 worker's ``wait_for_layer_load`` gates on.
        """
        self._require_store("load")
        chains = self._chains(token_ids)
        if first_block < 0 or first_block > len(chains):
            raise ValueError(
                f"first_block={first_block} outside the prompt's "
                f"{len(chains)} complete blocks"
            )
        # The prefix lookup is a blocking store round trip (native
        # get_match_last_index): on a remote store that is a full RTT, which
        # must not stall the event loop mid-wave (ITS-L001) — hop it through
        # the default executor; the sync ``lookup()`` path stays direct.
        hit = await asyncio.to_thread(self._lookup_chains, chains)
        n = min(hit - first_block, len(block_ids))
        if n <= 0:
            return list(caches), 0
        # Trace: the cached prefix's store streaming begins here (the probe
        # above is control-plane; fetch_start marks the first data-plane leg).
        tspan = tracing.active_span()
        if tspan is not None:
            tspan.stage("fetch_start")
            tspan.annotate(hit_blocks=hit, fetch_blocks=n)
        span = chains[first_block : first_block + n]
        try:
            out = await self._reader.read(
                caches, np.asarray(block_ids[:n]), self._key_fn(span),
                on_layer=on_layer,
            )
        except PartialReadError as e:
            # e.caches, not the original list: layers scattered before the
            # failure donated their input buffers (deleted on TPU).
            if isinstance(
                e.cause, (InfiniStoreKeyNotFound, InfiniStoreResourcePressure)
            ):
                # KeyNotFound: blocks raced away (eviction/delete between
                # lookup and read). ResourcePressure: store RAM too pressured
                # to promote/serve right now (507; the spilled data
                # survives). Cache semantics either way — the engine just
                # recomputes; transport errors still propagate (lookup()'s
                # contract), carrying the partial caches.
                if isinstance(e.cause, InfiniStoreColdTier):
                    # The typed 512: cold BUT ALIVE — a tier demotion hit,
                    # not a miss (the data is one tier down, and the tier
                    # stats must be able to tell the two apart;
                    # docs/tiering.md).
                    tiering_note_demotion_hit()
                return e.caches, 0
            raise
        return out, n

    def start_fetch(
        self,
        token_ids,
        first_block: int = 0,
        limit_blocks: Optional[int] = None,
        prefetch_pool: Optional[HostStagingPool] = None,
        priority: int = wire.PRIORITY_FOREGROUND,
        known_hit: Optional[int] = None,
        retry_missing_s: float = 0.0,
        retry_interval_s: float = 0.002,
        fetch_gate=None,
    ) -> LayerwisePrefetch:
        """Begin the GATE-FREE half of a load: probe the store (one control
        round trip) and immediately start streaming the hit prefix's layers
        into reserved host staging regions — no device work, no engine
        lock, callable before the engine has even allocated blocks. The
        returned :class:`~.tpu.layerwise.LayerwisePrefetch` carries
        ``hit_blocks`` (the lookup answer) and ``n_blocks`` (what is being
        fetched); ``install(caches, block_ids)`` is the short exclusive
        phase with ``load``'s exact semantics, and ``discard()`` cancels
        cleanly (staging accounting returns to baseline).

        Concurrent admissions' fetches coalesce into shared batched store
        reads (:class:`FetchCoalescer`), so a wave of requests splits
        striped connections instead of queueing serially.

        ``priority``: QoS class of the fetch's store reads. Admission-
        blocking fetches stay FOREGROUND (the default, untagged);
        engines tag a speculative prefetch for a request beyond the next
        wave ``wire.PRIORITY_BACKGROUND`` so it never delays
        decode-blocking reads (docs/qos.md). Same-class submissions still
        coalesce; classes never merge.

        ``retry_missing_s``: handoff read-racing-write mode (disagg.py).
        A decode engine fetching a prefix the prefill engine is STILL
        SHIPPING sees KeyNotFound for layers not yet published; with a
        nonzero deadline the prefetch re-probes missing keys instead of
        failing, so per-layer installs (``install_layer``) ride out the
        race. Zero (the default) keeps strict cache semantics: absent
        means miss. Retry mode bypasses the coalescer (each layer's reads
        go direct) so one stalled layer never wedges merged group-mates.
        ``retry_interval_s`` is the re-probe cadence — it bounds the
        quantization latency a just-published layer waits before its
        re-probe lands, so TTFT-critical handoffs pass a sub-millisecond
        interval. ``fetch_gate`` (``async fetch_gate(layer)``) is the
        announce-driven variant: when the producer signals per-layer
        publication, layer ``l``'s read waits for the announcement instead
        of blind-probing keys that cannot exist yet (a probe storm that
        contends with the very ships it is waiting on). Gated fetches also
        bypass the coalescer.

        Raises :class:`~.tpu.staging.StagingPoolExhausted` when the
        prefetch arena cannot hold another pipeline — callers treat that
        as backpressure and fall back to the one-phase ``load``. Must be
        called from a running event loop (the loop the install/discard
        will run on) — which also means the inline probe BLOCKS that loop
        for one store RTT; async callers should prefer
        :meth:`start_fetch_async`, which hops the probe through an
        executor (``known_hit`` is how it hands the answer back in)."""
        self._require_store("start_fetch")
        chains = self._chains(token_ids)
        if first_block < 0 or first_block > len(chains):
            raise ValueError(
                f"first_block={first_block} outside the prompt's "
                f"{len(chains)} complete blocks"
            )
        hit = self._lookup_chains(chains) if known_hit is None else known_hit
        n = max(0, hit - first_block)
        n = min(n, self.max_blocks)
        if limit_blocks is not None:
            n = min(n, limit_blocks)
        pool = prefetch_pool or self._ensure_prefetch_pool()
        # Trace: the gate-free layer streaming starts with the handle below.
        tspan = tracing.active_span()
        if tspan is not None and n > 0:
            tspan.stage("fetch_start")
            tspan.annotate(hit_blocks=hit, fetch_blocks=n)
        span = chains[first_block : first_block + n]
        # Mutable class cell so promote() upgrades LATER submissions even
        # on the coalescer path (the closure reads it per call).
        pri_cell = {"value": priority}
        if prefetch_pool is None and retry_missing_s <= 0 and fetch_gate is None:
            coalescer = self._ensure_coalescer(pool)
            submit = lambda blocks: coalescer.submit(
                blocks, priority=pri_cell["value"]
            )
        else:
            # Retry/gated modes go direct: a KeyNotFound re-probe loop (or
            # an announcement wait) inside a merged batch would re-drive —
            # or stall — its group-mates' reads too.
            submit = None
        try:
            handle = LayerwisePrefetch(
                self.conn,
                pool,
                self.spec,
                self._key_fn(span),
                n,
                self.spec.num_layers,
                submit=submit,
                priority=priority,
                # One shared cell: promote() on the handle flips the class
                # the coalescer closure reads too.
                priority_cell=pri_cell,
                retry_missing_s=retry_missing_s,
                retry_interval_s=retry_interval_s,
                fetch_gate=fetch_gate,
            )
        except StagingPoolExhausted as e:
            # The probe already ran — hand its answer to the fallback so a
            # backpressured admission (the most loaded moment) does not pay
            # the control round trip twice.
            e.hit_blocks = hit
            raise
        handle.hit_blocks = hit
        return handle

    async def start_fetch_async(
        self,
        token_ids,
        first_block: int = 0,
        limit_blocks: Optional[int] = None,
        prefetch_pool: Optional[HostStagingPool] = None,
        priority: int = wire.PRIORITY_FOREGROUND,
        known_hit: Optional[int] = None,
        retry_missing_s: float = 0.0,
        retry_interval_s: float = 0.002,
        fetch_gate=None,
    ) -> LayerwisePrefetch:
        """:meth:`start_fetch` for event-loop callers: the probe (a full
        store round trip) runs in the default executor, then the handle is
        built inline on the loop via ``known_hit`` — the fetch futures it
        starts need the running loop, so ONLY the probe may leave it.
        Mid-wave admission (vllm_v1 phase 1, the engine's install path)
        calls this so one request's lookup RTT never stalls the wave's
        other reads (ITS-L001, docs/static_analysis.md).

        ``known_hit`` skips the probe entirely — the overlapped handoff
        path (disagg.py) passes the block count the prefill side announced,
        because a store probe during an in-flight handoff would see only
        the layers published so far (layer 0 ships FIRST there, and it IS
        the sentinel, so the probe is also racy-optimistic)."""
        self._require_store("start_fetch")
        if known_hit is None:
            known_hit = await asyncio.to_thread(
                self._lookup_chains, self._chains(token_ids)
            )
        return self.start_fetch(
            token_ids, first_block=first_block, limit_blocks=limit_blocks,
            prefetch_pool=prefetch_pool, priority=priority,
            known_hit=known_hit, retry_missing_s=retry_missing_s,
            retry_interval_s=retry_interval_s, fetch_gate=fetch_gate,
        )

    def _ensure_prefetch_pool(self) -> HostStagingPool:
        if self._prefetch_pool is None:
            # ~4 full-depth pipelines (capped at 8 regions each, matching
            # LayerwisePrefetch's default): enough for a concurrent
            # admission wave; an over-wave falls back to the gated load.
            regions = min(self.spec.num_layers, 8)
            nbytes = 4 * regions * 2 * self.max_blocks * self.spec.block_nbytes
            self._prefetch_pool = HostStagingPool(
                nbytes, self.spec.block_nbytes, conn=self.conn
            )
        return self._prefetch_pool

    def _ensure_coalescer(self, pool: HostStagingPool) -> FetchCoalescer:
        if self._coalescer is None or self._coalescer.base_ptr != pool.base_ptr:
            self._coalescer = FetchCoalescer(
                self.conn, self.spec.block_nbytes, pool.base_ptr
            )
        return self._coalescer

    def stage_layer_save(
        self, token_ids, layer: int, kv_pair, block_ids: np.ndarray,
        first_block: int = 0, priority: int = wire.PRIORITY_BACKGROUND,
    ):
        """Stage ONE layer's computed blocks for saving; returns ``ship``,
        an async callable performing the network puts (2*n blocks written).

        The gather + async D2H start NOW, on the caller's thread — the
        bytes are snapshotted before later compute (or the next step) can
        perturb the cache — while ``ship()`` does only awaits (the D2H
        wait runs in an executor so it never stalls the caller's event
        loop). This is the layer-granular half of ``save()`` for engines
        that stream saves as each layer's forward completes (the vLLM v1
        worker, vllm_v1.py): such callers MUST ship layer 0 last — its
        keys are the whole-block presence sentinel (``lookup``), so
        shipping it before deeper layers commit would publish a half-saved
        block. Whole-request saves should use ``save()``, whose writer
        enforces that ordering internally.

        ``priority``: QoS class of the puts (docs/qos.md). Layer-streamed
        saves default BACKGROUND — they run behind the engine's forward
        pass and must never delay a decode-blocking fetch. A prefill→decode
        HANDOFF ship passes ``wire.PRIORITY_FOREGROUND``: its consumer is
        actively waiting on these exact bytes (disagg.py), so background
        class would delay the reader it feeds. Disagg producers must name
        the class explicitly at the call site (ITS-P004,
        docs/static_analysis.md).

        Tracing: the CALLER's active span (captured now, not at ship time)
        rides the ship — one trace id covers prefill compute → store puts →
        decode install. The ship stamps ``submit`` when its puts issue."""
        self._require_store("stage_layer_save")
        import jax.numpy as jnp

        from .tpu.paged import gather_blocks

        chains = self._chains(token_ids)
        if first_block < 0 or first_block > len(chains):
            # Same bounds contract as save()/load(): an out-of-range
            # first_block would silently slice to an empty chain list and
            # return a no-op ship, hiding the caller's bug.
            raise ValueError(
                f"first_block={first_block} outside the prompt's "
                f"{len(chains)} complete blocks"
            )
        chains = chains[first_block:]
        n = min(len(chains), len(block_ids))
        if n == 0:
            async def noop() -> int:
                return 0

            return noop
        k_cache, v_cache = kv_pair
        bn = self.spec.block_nbytes
        ids_dev = jnp.asarray(np.asarray(block_ids[:n]), dtype=jnp.int32)
        # One packed [K blocks | V blocks] span -> one D2H transfer (the
        # writer's shape, tpu/layerwise.py).
        tr = self.pool.stage_out([
            jnp.concatenate([
                gather_blocks(k_cache, ids_dev),
                gather_blocks(v_cache, ids_dev),
            ])
        ])
        keys_k = [(self.block_key(layer, "k", chains[i]), i * bn) for i in range(n)]
        keys_v = [(self.block_key(layer, "v", chains[i]), (n + i) * bn) for i in range(n)]
        pri_kw = wire.qos_kwargs(self.conn, priority)
        # Capture the request's trace context HERE: ship() typically runs as
        # a free-floating task whose contextvars are whatever scheduled it,
        # not the request that staged this layer.
        span = tracing.active_span()

        async def ship() -> int:
            loop = asyncio.get_running_loop()
            (kv_host,) = await loop.run_in_executor(None, tr.wait)
            base = kv_host.ctypes.data
            if span is not None:
                span.stage("submit")
                span.annotate(handoff_layer=layer, handoff_blocks=2 * n)
            try:
                with tracing.override_span(span):
                    await asyncio.gather(
                        self.conn.write_cache_async(keys_k, bn, base, **pri_kw),
                        self.conn.write_cache_async(keys_v, bn, base, **pri_kw),
                    )
            finally:
                tr.release()
            return 2 * n

        return ship

    async def handoff(
        self,
        token_ids,
        caches,
        src_block_ids: np.ndarray,
        dst_block_ids: np.ndarray,
        src: Optional[int] = None,
        dst: Optional[int] = None,
    ):
        """Move a request's KV blocks from a producer to a consumer — one
        API, two transports (reference has only its NIC transport; on TPU
        pods the interconnect is the fast path).

        Same-mesh (``ici`` bound and ``src``/``dst`` shard indices given):
        gather + ppermute + scatter for ALL layers fused into ONE jitted
        SPMD program with a single collective (IciBlockTransfer.
        handoff_layers) — HBM->HBM over ICI, no host, no store, one launch.
        ``caches`` must be per-layer (K, V) arrays of shape
        [axis_size, num_blocks, *block] sharded over the transfer axis, with
        a uniform shape/dtype across layers (ragged layers raise ValueError);
        inputs are donated (use the returned caches).

        Otherwise: degrades to the DCN store — save the blocks under the
        request's chain keys, then load them into ``dst_block_ids`` (the
        cross-process flow runs save on the producer and load on the
        consumer; calling handoff on one process does both for tests and
        single-engine reuse). ``caches`` are plain [num_blocks, *block]
        arrays here.

        Returns (updated caches, blocks moved).
        """
        # Both transports move the same amount: the request's COMPLETE token
        # blocks (an incomplete tail block has no chain key, so the DCN path
        # could never carry it — the ICI path must agree or a cross-mesh
        # fallback would silently serve different data).
        chains = self._chains(token_ids)
        n = min(len(src_block_ids), len(dst_block_ids), len(chains))
        if n == 0:
            return list(caches), 0
        if self.ici is not None and src is not None and dst is not None:
            flat = [c for kv in caches for c in kv]
            uniform = all(
                c.shape == flat[0].shape and c.dtype == flat[0].dtype for c in flat
            )
            if uniform:
                # All layers in ONE SPMD launch (single collective over the
                # stacked blocks) — a per-layer loop here would pay L
                # sequential dispatch round-trips on the latency-critical path.
                out = self.ici.handoff_layers(
                    list(caches), src_block_ids[:n], dst_block_ids[:n], src, dst
                )
            else:
                # Ragged layers (hybrid architectures: sliding-window layers
                # with fewer blocks, mixed precision) cannot stack into one
                # collective — fall back to one fused K+V launch per layer.
                out = [
                    self.ici.handoff_kv(
                        k, v, src_block_ids[:n], dst_block_ids[:n], src, dst
                    )
                    for k, v in caches
                ]
            return out, n
        if self.ici is not None and self.conn is None:
            raise ValueError(
                "pure-ICI connector: handoff needs src and dst shard indices "
                "(no store connection to fall back to)"
            )
        self._require_store("handoff (DCN fallback)")
        # The DCN path gathers along axis 0 = blocks, so an ICI-layout cache
        # ([axis_size, num_blocks, *block] — one extra leading dim) would be
        # gathered along the DEVICE axis and ship wrong bytes under valid
        # keys. Reject it loudly instead of corrupting silently.
        want = 1 + len(self.spec.block_shape)  # [num_blocks, *block]
        for k_cache, v_cache in caches:
            for c in (k_cache, v_cache):
                if c.ndim != want or tuple(c.shape[1:]) != tuple(self.spec.block_shape):
                    raise ValueError(
                        "handoff DCN fallback needs per-layer caches of shape "
                        f"[num_blocks, {', '.join(map(str, self.spec.block_shape))}]; "
                        f"got {tuple(c.shape)}. ICI-layout caches "
                        "([axis_size, num_blocks, *block]) require src and dst "
                        "shard indices so the transfer rides the interconnect."
                    )
        # FOREGROUND: a handoff's consumer is actively waiting on this save
        # (it loads the same blocks next) — background class would delay
        # exactly the reader it feeds.
        await self.save(
            token_ids, caches, np.asarray(src_block_ids)[:n],
            priority=wire.PRIORITY_FOREGROUND,
        )
        return await self.load(token_ids, caches, np.asarray(dst_block_ids)[:n])

    def get_stats(self) -> dict:
        """The store connection's per-op stats snapshot (observability
        surface composed members re-expose — cluster.py stats())."""
        self._require_store("get_stats")
        return self.conn.get_stats()

    def drop(self, token_ids) -> int:
        """Remove this prompt's blocks from the store (all layers). Returns
        the number of store keys deleted."""
        self._require_store("drop")
        chains = self._chains(token_ids)
        keys = [
            self.block_key(layer, kind, c)
            for layer in range(self.spec.num_layers)
            for kind in ("k", "v")
            for c in chains
        ]
        return self.conn.delete_keys(keys) if keys else 0
