"""ctypes loader for the native core (libinfinistore_tpu.so).

Replaces the reference's pybind11 extension module
(reference src/pybind.cpp) — see native/src/c_api.cpp for why ctypes.
The library is built by `make -C native` (done automatically here when the .so
is missing or older than the sources).
"""

import ctypes
import os
import subprocess
from ctypes import (
    CFUNCTYPE,
    POINTER,
    c_char_p,
    c_double,
    c_int,
    c_int32,
    c_int64,
    c_uint8,
    c_uint32,
    c_uint64,
    c_void_p,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SO_PATH = os.path.join(_HERE, "libinfinistore_tpu.so")
_NATIVE_DIR = os.path.join(_REPO, "native")

# Completion callback: (ctx, status_code). ctypes re-acquires the GIL when the
# reactor thread calls back into Python (the pybind equivalent needed explicit
# gil_scoped_acquire; here it is automatic).
COMPLETION_CB = CFUNCTYPE(None, c_void_p, c_int)
LOG_SINK_CB = CFUNCTYPE(None, c_int, c_char_p)


def _needs_build() -> bool:
    if not os.path.exists(_SO_PATH):
        return True
    if not os.path.isdir(_NATIVE_DIR):
        return False  # installed wheel: .so shipped, no sources
    so_mtime = os.path.getmtime(_SO_PATH)
    for root, _dirs, files in os.walk(_NATIVE_DIR):
        for f in files:
            if f.endswith((".cpp", ".h")) and os.path.getmtime(os.path.join(root, f)) > so_mtime:
                return True
    return False


def _build() -> None:
    subprocess.run(
        ["make", "-s", "-j", str(os.cpu_count() or 2)],
        cwd=_NATIVE_DIR,
        check=True,
        capture_output=True,
    )


if _needs_build():
    _build()

# Older glibc keeps shm_open/shm_unlink in librt; a .so built against a glibc
# that folded them into libc then fails to load with "undefined symbol:
# shm_open". Preloading librt globally resolves the symbols either way.
try:
    ctypes.CDLL("librt.so.1", mode=ctypes.RTLD_GLOBAL)
except OSError:
    pass  # no librt (musl / new glibc): the symbols live in libc already

lib = ctypes.CDLL(_SO_PATH)

# ---- logging ----
lib.its_set_log_level.argtypes = [c_int]
lib.its_set_log_sink.argtypes = [LOG_SINK_CB]
lib.its_log.argtypes = [c_int, c_char_p]

# ---- server ----
lib.its_server_create.argtypes = [
    c_char_p, c_int, c_uint64, c_uint64, c_int, c_uint64, c_int, c_double, c_double, c_int,
    c_int, c_char_p, c_uint64,
]
lib.its_server_create.restype = c_void_p
lib.its_server_start.argtypes = [c_void_p]
lib.its_server_start.restype = c_int
lib.its_server_stop.argtypes = [c_void_p]
lib.its_server_destroy.argtypes = [c_void_p]
lib.its_server_port.argtypes = [c_void_p]
lib.its_server_port.restype = c_int
lib.its_server_kvmap_len.argtypes = [c_void_p]
lib.its_server_kvmap_len.restype = c_uint64
lib.its_server_purge.argtypes = [c_void_p]
lib.its_server_purge.restype = c_uint64
lib.its_server_evict.argtypes = [c_void_p, c_double, c_double]
lib.its_server_evict.restype = c_uint64
lib.its_server_usage.argtypes = [c_void_p]
lib.its_server_usage.restype = c_double
lib.its_server_stats_json.argtypes = [c_void_p, c_char_p, c_int]
lib.its_server_stats_json.restype = c_int

# ---- client ----
# Trailing two ints: enable_ring (descriptor-ring data plane,
# docs/descriptor_ring.md) and ring_slots (0 = native default).
lib.its_conn_create.argtypes = [
    c_char_p, c_int, c_int, c_int, c_int, c_int, c_int, c_int,
]
lib.its_conn_create.restype = c_void_p
lib.its_conn_connect.argtypes = [c_void_p]
lib.its_conn_connect.restype = c_int
lib.its_conn_shm_active.argtypes = [c_void_p]
lib.its_conn_shm_active.restype = c_int
lib.its_conn_ring_active.argtypes = [c_void_p]
lib.its_conn_ring_active.restype = c_int
lib.its_conn_ring_name.argtypes = [c_void_p, c_char_p, c_int]
lib.its_conn_ring_name.restype = c_int
# Client ring ledger: posted, doorbells, full fallbacks, meta fallbacks,
# completions (lib.InfinityConnection.ring_stats).
lib.its_conn_ring_counters.argtypes = [
    c_void_p, POINTER(c_uint64), POINTER(c_uint64), POINTER(c_uint64),
    POINTER(c_uint64), POINTER(c_uint64),
]
# PR 16 mechanism ledger: batch slots, batch ops, reactor poll hits, poll
# arms (its_conn_ring_counters keeps its 5-value shape for stability).
lib.its_conn_ring_poll_counters.argtypes = [
    c_void_p, POINTER(c_uint64), POINTER(c_uint64), POINTER(c_uint64),
    POINTER(c_uint64),
]
# Multi-op batch grouping: bracket one event-loop tick's ring posts so a
# coalesced flush publishes as one batch slot (docs/descriptor_ring.md).
lib.its_conn_ring_group_begin.argtypes = [c_void_p]
lib.its_conn_ring_group_end.argtypes = [c_void_p]
lib.its_conn_close.argtypes = [c_void_p]
lib.its_conn_destroy.argtypes = [c_void_p]
lib.its_conn_connected.argtypes = [c_void_p]
lib.its_conn_connected.restype = c_int
lib.its_conn_register_mr.argtypes = [c_void_p, c_void_p, c_uint64]
lib.its_conn_register_mr.restype = c_int
lib.its_conn_unregister_mr.argtypes = [c_void_p, c_void_p]
lib.its_conn_unregister_mr.restype = c_int
lib.its_conn_alloc_shm_mr.argtypes = [c_void_p, c_uint64]
lib.its_conn_alloc_shm_mr.restype = c_void_p
# Trailing c_int: QoS class tag (0 = foreground/default, 1 = background —
# wire.PRIORITY_*; see docs/qos.md). The two trailing c_uint64s are the
# per-op trace context (trace id + client span id, docs/observability.md);
# 0/0 = untraced, zero extra wire bytes.
_batch_args = [
    c_void_p, c_char_p, c_uint64, c_uint32, POINTER(c_uint64), c_uint32, c_void_p,
    COMPLETION_CB, c_void_p, c_int, c_uint64, c_uint64,
]
lib.its_conn_put_batch.argtypes = _batch_args
lib.its_conn_put_batch.restype = c_int
lib.its_conn_get_batch.argtypes = _batch_args
lib.its_conn_get_batch.restype = c_int
_batch_sync_args = [
    c_void_p, c_char_p, c_uint64, c_uint32, POINTER(c_uint64), c_uint32, c_void_p, c_int,
    c_uint64, c_uint64,
]
lib.its_conn_put_batch_sync.argtypes = _batch_sync_args
lib.its_conn_put_batch_sync.restype = c_int
lib.its_conn_get_batch_sync.argtypes = _batch_sync_args
lib.its_conn_get_batch_sync.restype = c_int
lib.its_conn_tcp_put.argtypes = [c_void_p, c_char_p, c_void_p, c_uint64]
lib.its_conn_tcp_put.restype = c_int
lib.its_conn_tcp_get.argtypes = [c_void_p, c_char_p, POINTER(POINTER(c_uint8)), POINTER(c_uint64)]
lib.its_conn_tcp_get.restype = c_int
lib.its_free.argtypes = [c_void_p]
lib.its_conn_check_exist.argtypes = [c_void_p, c_char_p]
lib.its_conn_check_exist.restype = c_int
lib.its_conn_match_last_index.argtypes = [c_void_p, c_char_p, c_uint64, c_uint32]
lib.its_conn_match_last_index.restype = c_int32
lib.its_conn_delete_keys.argtypes = [c_void_p, c_char_p, c_uint64, c_uint32]
lib.its_conn_delete_keys.restype = c_int64
lib.its_conn_stat_json.argtypes = [c_void_p, c_char_p, c_int]
lib.its_conn_stat_json.restype = c_int
# Event-fd completion ring (fd owned by the Python side; never closed natively).
lib.its_conn_set_completion_fd.argtypes = [c_void_p, c_int]
lib.its_conn_drain_completions.argtypes = [
    c_void_p, POINTER(c_uint64), POINTER(c_int32), c_int,
]
lib.its_conn_drain_completions.restype = c_int
# Wakeup-coalescing counters: ring pushes vs eventfd writes (empty->non-empty
# transitions only), the completion_batch_size numerator/denominator.
lib.its_conn_completion_counters.argtypes = [
    c_void_p, POINTER(c_uint64), POINTER(c_uint64),
]

# ---- mempool (unit-test surface) ----
lib.its_mm_create.argtypes = [c_uint64, c_uint64, c_int]
lib.its_mm_create.restype = c_void_p
lib.its_mm_destroy.argtypes = [c_void_p]
lib.its_mm_allocate.argtypes = [c_void_p, c_uint64, c_uint32, POINTER(c_void_p)]
lib.its_mm_allocate.restype = c_int
lib.its_mm_deallocate.argtypes = [c_void_p, c_void_p, c_uint64]
lib.its_mm_usage.argtypes = [c_void_p]
lib.its_mm_usage.restype = c_double
lib.its_mm_extend.argtypes = [c_void_p, c_uint64]
lib.its_mm_extend.restype = c_int
lib.its_mm_total_bytes.argtypes = [c_void_p]
lib.its_mm_total_bytes.restype = c_uint64
lib.its_mm_used_bytes.argtypes = [c_void_p]
lib.its_mm_used_bytes.restype = c_uint64
lib.its_mm_pinned.argtypes = [c_void_p]
lib.its_mm_pinned.restype = c_int
