"""Overlapped layerwise prefill→decode handoff (the disaggregation plane).

The blocking disaggregation baseline (tests/test_engine_disagg.py) is
store-and-forward: the prefill engine computes ALL layers, saves, and only
then may the decode engine fetch ALL layers before its first step. This
module overlaps the three legs end to end:

  prefill engine                    store                   decode engine
  ─────────────────                 ─────                   ─────────────
  layer 0 compute ──ship 0──▶ keys published ──fetch 0──▶ install 0
  layer 1 compute ──ship 1──▶        ...        ──fetch 1──▶ install 1
       ...            (layer l ships WHILE l+1 computes)        ...
                                                first decode step launches
                                                once layer 0 installs; its
                                                layer-l attention waits only
                                                on layer l's install.

* ``stream_prefill`` chains the per-layer jitted ``prefill_layer`` and hands
  each layer's freshly scattered KV to ``KVConnector.stage_layer_save`` AS
  COMPUTED — layer ``l``'s store puts overlap layer ``l+1``'s compute. The
  ships are HANDOFF traffic: tagged ``wire.PRIORITY_FOREGROUND`` at the call
  site (a decode consumer is actively waiting on these exact bytes; ITS-P004
  requires disagg producers to name the class) and they carry the request's
  trace context, so ONE trace id covers prefill compute → store puts →
  decode install. Layers ship in NATURAL order 0..L-1 — layer 0 (the
  ``lookup`` sentinel) is published first, deliberately: the consumer is not
  probing (``known_hit``), and any OTHER reader that races the handoff hits
  ``KeyNotFound`` on a deeper layer, which ``load`` maps to a miss →
  recompute (cache semantics, never wrong bytes).

* ``overlapped_decode`` is the layerwise admission: ``start_fetch_async``
  with ``retry_missing_s`` (read-racing-write mode) returns per-layer
  handles, and the WATERMARK rule gates compute — the first decode step
  launches once layers ``[0, watermark)`` are installed while deeper layers
  are still in flight; inside the step, layer ``l``'s attention calls
  ``install_layer(l)`` first. ``watermark=n_layers`` degenerates to today's
  blocking fetch-all. A late/failed layer triggers the layer-chunked local
  recompute fallback (``_recompute_prefix``): never wrong bytes, counted in
  ``disagg_fallback_recomputes``, journaled as a ``disagg_fallback`` event.

* Byte identity is BY CONSTRUCTION: the watermarked and blocking paths chain
  the same jitted ``decode_wave_layer`` programs, and the streamed prefill
  and the fallback recompute chain the same jitted ``prefill_layer``
  programs — identical executables, bitwise-identical logits and caches.

* ``DisaggHarness`` is the two-engine rig: one prefill-side and one
  decode-side :class:`~.connector.KVConnector` (separate store connections,
  separate block layouts) driving the four TTFT legs the bench gates
  (overlapped / blocking fetch-all / local recompute / cold fetch), plus the
  ``python -m infinistore_tpu.disagg`` prefill subprocess role for the chaos
  test (tools/fleet.py spawn pattern; ``--stall-after-layer`` pins the
  kill -9 window mid-handoff).

Counters are the ``disagg_*`` vocabulary (ITS-C009 lockstep with the
/metrics exporter and docs/disaggregation.md).
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from . import telemetry, tracing, wire
from .connector import KVConnector
from .models.llama import (
    LlamaConfig,
    decode_wave_layer,
    embed_prompt,
    embed_wave,
    init_params,
    lm_logits,
    prefill_layer,
)

__all__ = [
    "DisaggCounters",
    "DisaggHarness",
    "counters",
    "demo_config",
    "demo_prompt",
    "local_decode",
    "overlapped_decode",
    "reset_counters",
    "stream_prefill",
]


class DisaggCounters:
    """The disaggregation plane's counter ledger (ITS-C009).

    One instance per process (module singleton via :func:`counters`); both
    roles bump their own side — a prefill engine counts the handoffs it
    ships, a decode engine the admissions it gates — and the manage-plane
    exporter (server.py ``_disagg_prometheus_lines``) publishes whatever
    this process accumulated. Key vocabulary (every key ``disagg_``-prefixed,
    documented in docs/disaggregation.md):

    - ``disagg_handoffs``: overlapped handoff legs this process initiated
      (producer ships + consumer admissions each count their own side).
    - ``disagg_overlap_layers``: layers whose fetch was still in flight when
      the first decode step launched AND that installed mid-step — the
      mechanism proof the bench gates on (≥1 means the first token really
      overlapped the transfer).
    - ``disagg_watermark_stalls``: residual waits the overlap could not
      hide — compute reaching a layer before its bytes (``wait_stalls``)
      plus read-racing-write re-probes (``retry_stalls``).
    - ``disagg_fallback_recomputes``: late/failed layers that fell back to
      the local layer-chunked recompute (never wrong bytes, just work).
    - ``disagg_inflight_at_first_token``: layers not yet staged when the
      first decode step launched (depth of the pipeline at launch).
    - ``disagg_wrong_bytes``: verification mismatches between a handoff
      decode and the local-recompute oracle. MUST stay 0; a nonzero value
      is a correctness bug, not a performance signal.
    """

    def __init__(self):
        # Written only on the role's engine loop (prefill ships / decode
        # admits on their own asyncio loop); the manage-plane server
        # thread snapshots via status().
        # its: guard[_c: single_writer]
        self._c = {
            "disagg_handoffs": 0,
            "disagg_overlap_layers": 0,
            "disagg_watermark_stalls": 0,
            "disagg_fallback_recomputes": 0,
            "disagg_inflight_at_first_token": 0,
            "disagg_wrong_bytes": 0,
        }

    def bump(self, key: str, n: int = 1) -> None:
        self._c[key] += n

    def status(self) -> dict:
        """Counter snapshot for /metrics and /disagg (explicit literal so
        the ITS-C009 ledger scan reads the full vocabulary here too)."""
        c = self._c
        return {
            "disagg_handoffs": c["disagg_handoffs"],
            "disagg_overlap_layers": c["disagg_overlap_layers"],
            "disagg_watermark_stalls": c["disagg_watermark_stalls"],
            "disagg_fallback_recomputes": c["disagg_fallback_recomputes"],
            "disagg_inflight_at_first_token": c["disagg_inflight_at_first_token"],
            "disagg_wrong_bytes": c["disagg_wrong_bytes"],
        }


_COUNTERS = DisaggCounters()


def counters() -> DisaggCounters:
    """This process's disagg counter ledger (what /metrics exports)."""
    return _COUNTERS


def reset_counters() -> DisaggCounters:
    """Fresh ledger (tests/bench legs isolate their counts)."""
    global _COUNTERS
    _COUNTERS = DisaggCounters()
    return _COUNTERS


def demo_config(
    n_layers: int = 6, block_tokens: int = 8, dim: int = 64,
    ffn_dim: int = 128,
) -> LlamaConfig:
    """The demo model BOTH roles must agree on: the prefill subprocess and
    the in-proc decode side derive identical params (same seed), identical
    chain hashes, and identical jitted per-layer programs from this one
    config — which is what makes the handoff byte-checkable end to end.

    ``dim``/``ffn_dim`` scale the per-layer prefill compute; the bench leg
    raises them so prefill is genuinely slower than a layer's fetch+install
    (the regime where layerwise overlap pays — with a dispatch-bound toy
    model every leg degenerates to the same store-bound chain)."""
    return LlamaConfig(
        vocab=128, dim=dim, n_layers=n_layers, n_heads=4, n_kv_heads=2,
        ffn_dim=ffn_dim, block_tokens=block_tokens, dtype=jnp.float32,
    )


def demo_prompt(config: LlamaConfig, n_blocks: int, seed: int = 0) -> List[int]:
    """Deterministic prompt of ``n_blocks`` complete blocks; ``seed`` varies
    the content (and therefore the chain hashes — each bench round uses a
    fresh prompt so its fetch really races its ship, instead of hitting the
    previous round's keys)."""
    n = n_blocks * config.block_tokens
    return ((np.arange(n) * 37 + seed * 101) % config.vocab).tolist()


# -- prefill side ------------------------------------------------------------


async def stream_prefill(
    connector,
    params,
    config: LlamaConfig,
    prompt: Sequence[int],
    caches,
    block_table: np.ndarray,
    *,
    on_layer_shipped=None,
    stall_after_layer: Optional[int] = None,
    stall_s: float = 0.0,
    crash_after_layers: Optional[int] = None,
    max_inflight_ships: int = 4,
    pace_s: float = 0.0,
):
    """Prefill the prompt layer by layer, shipping each layer's KV to the
    store AS COMPUTED: layer ``l``'s store puts overlap layer ``l+1``'s
    compute (JAX async dispatch keeps the device busy while ``ship()``
    awaits the network). Returns ``(last-token logits, caches, blocks
    written)``.

    Ships are handoff traffic: ``wire.PRIORITY_FOREGROUND`` named at the
    call site (ITS-P004 — a decode consumer is actively waiting on these
    bytes) and the caller's active span rides every ship, so the decode
    side's installs continue the same trace. Layers go out in natural order
    0..L-1 (module docstring: sentinel-first is safe here).

    ``max_inflight_ships`` bounds concurrently staged layers so the
    connector's host staging pool (sized for ~6 layer spans) never
    exhausts on deep models; the oldest ship is awaited before staging
    past the bound.

    Chaos hooks (the ``python -m infinistore_tpu.disagg`` subprocess wires
    them to flags): ``stall_after_layer=k`` makes layers ``0..k`` durable
    then sleeps ``stall_s`` — the window the chaos test kill -9s into;
    ``crash_after_layers=n`` makes the first ``n`` layers durable then
    SIGKILLs this process (no cleanup, mid-handoff by construction).
    ``on_layer_shipped(layer)`` fires after THAT layer's puts complete
    (durable when called — the subprocess prints its progress markers from
    it).

    ``pace_s`` emulates a DEDICATED prefill engine's per-layer production
    rate: after each layer's compute, sleep ``pace_s`` before shipping it.
    A real disaggregated deployment runs prefill on its own machine, so
    its compute never contends with the decode host; on a shared-core CI
    box an un-paced prefill time-slices against the decode process and a
    TTFT comparison measures scheduler contention, not pipeline overlap.
    The sleep keeps the bytes, keys, and announce protocol fully real
    (byte-identity is still checked) while leaving the core idle exactly
    when a remote engine would — the regime the bench leg measures.
    ``pace_s=0`` (the default, and all tests) disables it."""
    ds = counters()
    ds.bump("disagg_handoffs")
    span = tracing.active_span()
    if span is not None:
        span.annotate(
            handoff_layers=config.n_layers, handoff_prefix_blocks=len(block_table)
        )
    tokens = jnp.asarray(np.asarray(prompt, np.int32))
    table_dev = jnp.asarray(np.asarray(block_table), jnp.int32)
    ids = np.asarray(block_table)
    x = embed_prompt(params, tokens)
    out = list(caches)
    ships: List[asyncio.Future] = []
    pending = collections.deque()

    async def _shipped(layer: int, ship) -> int:
        written = await ship()
        if on_layer_shipped is not None:
            on_layer_shipped(layer)
        return written

    for layer in range(config.n_layers):
        x, k_cache, v_cache = prefill_layer(
            params, x, out[layer][0], out[layer][1], table_dev, config, layer
        )
        out[layer] = (k_cache, v_cache)
        if pace_s > 0.0:
            # Emulated remote-engine production rate (docstring): the
            # layer is computed; hold its ship to the paced cadence.
            await asyncio.sleep(pace_s)
        if len(pending) >= max_inflight_ships:
            await pending.popleft()
        ship = connector.stage_layer_save(
            prompt, layer, out[layer], ids,
            # HANDOFF class, named at source (ITS-P004): the decode engine
            # is already waiting on these exact bytes — background class
            # would delay the reader this ship feeds.
            priority=wire.PRIORITY_FOREGROUND,
        )
        fut = asyncio.ensure_future(_shipped(layer, ship))
        ships.append(fut)
        pending.append(fut)
        if stall_after_layer is not None and layer == stall_after_layer:
            await asyncio.gather(*ships)  # layers 0..k durable before the window
            await asyncio.sleep(stall_s)
        if crash_after_layers is not None and layer + 1 >= crash_after_layers:
            await asyncio.gather(*ships)
            import os
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        # Yield the loop so the staged ship's puts issue while the next
        # layer's dispatch proceeds — THE producer-side overlap.
        await asyncio.sleep(0)
    written = sum(await asyncio.gather(*ships))
    return lm_logits(params, x)[0, -1], out, written


# -- decode side -------------------------------------------------------------


@dataclasses.dataclass
class DecodeResult:
    """One decode leg's outcome: greedy ``tokens``, the bitwise
    ``first_logits`` the oracle comparison uses, the updated caches, the
    ``time.perf_counter()`` instant the first token's logits were ready
    (the harness subtracts its request-arrival t0 for TTFT), and the
    overlap accounting that feeds the ``disagg_*`` counters."""

    tokens: List[int]
    first_logits: np.ndarray
    caches: list
    t_first: float
    fallback: bool
    overlap_layers: int
    inflight_at_first_token: int
    watermark_stalls: int


def _recompute_prefix(params, config: LlamaConfig, prompt, caches, table_dev):
    """Layer-chunked local recompute of the whole prefix into ``table_dev``'s
    blocks — the fallback leg AND the local baseline. Chains the same jitted
    ``prefill_layer`` programs the prefill engine streams through, so the
    bytes are identical to a successful handoff (scatter touches only the
    prefix blocks: a decode step's writes into its own spare block
    survive)."""
    x = embed_prompt(params, jnp.asarray(np.asarray(prompt, np.int32)))
    out = list(caches)
    for layer in range(config.n_layers):
        x, k_cache, v_cache = prefill_layer(
            params, x, out[layer][0], out[layer][1], table_dev, config, layer
        )
        out[layer] = (k_cache, v_cache)
    return out


async def _run_decode_steps(
    params,
    config: LlamaConfig,
    state: dict,
    block_table: np.ndarray,
    first_token: int,
    start_pos: int,
    gen_tokens: int,
    max_blocks: int,
    ensure_layer=None,
    trace_events=None,
):
    """Greedy decode over ``state["out"]`` caches with the layerwise wave
    chain. ``ensure_layer(l)`` (first step only) is the watermark gate —
    it may swap ``state["out"]`` under us (install donates, fallback
    recomputes), which is why the cache list lives in the shared ``state``
    dict rather than a local. Returns ``(tokens, first_logits, t_first)``."""
    tables = jnp.asarray(np.asarray(block_table), jnp.int32)[None]
    tok = int(first_token)
    pos = start_pos
    tokens_out: List[int] = []
    first_logits = None
    t_first = 0.0
    for step in range(gen_tokens):
        x = embed_wave(params, jnp.asarray([[tok]], jnp.int32))
        positions = jnp.full((1, 1), pos, jnp.int32)
        for layer in range(config.n_layers):
            if step == 0 and ensure_layer is not None:
                await ensure_layer(layer)
            if step == 0 and trace_events is not None:
                trace_events.append(("compute", layer))
            x, k_cache, v_cache = decode_wave_layer(
                params, x, positions, state["out"][layer][0],
                state["out"][layer][1], tables, config, layer, max_blocks,
            )
            state["out"][layer] = (k_cache, v_cache)
        logits = lm_logits(params, x)[0, -1]
        if step == 0:
            first_logits = np.asarray(jax.block_until_ready(logits))
            t_first = time.perf_counter()
        tok = int(jnp.argmax(logits))
        tokens_out.append(tok)
        pos += 1
    return tokens_out, first_logits, t_first


async def overlapped_decode(
    connector,
    params,
    config: LlamaConfig,
    prompt: Sequence[int],
    caches,
    block_ids: np.ndarray,
    block_table: np.ndarray,
    first_token: int,
    *,
    watermark: int = 1,
    known_hit: Optional[int] = None,
    retry_missing_s: float = 2.0,
    retry_interval_s: float = 0.0003,
    fetch_gate=None,
    gen_tokens: int = 1,
    trace_events=None,
) -> DecodeResult:
    """Watermark-gated decode admission over an (possibly still in-flight)
    handoff prefix. ``block_ids`` are the decode engine's physical blocks
    for the prefix; ``block_table`` is the padded per-request table row
    (prefix + generation blocks) every ``decode_wave_layer`` call sees.

    The WATERMARK rule: layers ``[0, watermark)`` install before the first
    decode step launches; past the watermark, layer ``l``'s attention
    awaits ``install_layer(l)`` inline — it never reads bytes still in
    flight, and layers deeper than the one being computed keep streaming
    behind it. ``watermark=config.n_layers`` is the blocking fetch-all
    degenerate case (today's behavior, bitwise-identical logits — same
    jitted programs).

    ``known_hit`` MUST be the producer-announced block count (a store probe
    mid-handoff is racy — connector.start_fetch_async docstring);
    ``retry_missing_s`` is the read-racing-write deadline. ``fetch_gate``
    (``async fetch_gate(layer)``) is the announce-driven mode: when the
    producer can signal per-layer publication (in-process harness, or a
    control channel), layer ``l``'s store read waits for the announcement
    instead of blind re-probing — without it, every layer's fetch polls
    keys that cannot exist yet, a probe storm contending with the very
    ships it waits on. The retry deadline still rides any residual race. A layer missing
    past the deadline (or a store failure) flips the leg to the
    layer-chunked local recompute fallback — ``disagg_fallback_recomputes``
    counts it, a ``disagg_fallback`` journal event records it, and the
    bytes are identical by construction, so correctness never depends on
    the race.

    ``trace_events`` (tests): appended with ``("install", l)`` /
    ``("compute", l)`` tuples — the watermark invariant is that every
    layer's install precedes its compute."""
    n_layers = config.n_layers
    n_blocks = len(block_ids)
    wm = max(1, min(watermark, n_layers))
    ds = counters()
    ds.bump("disagg_handoffs")
    span = tracing.active_span()
    handle = await connector.start_fetch_async(
        prompt,
        limit_blocks=n_blocks,
        known_hit=known_hit if known_hit is not None else n_blocks,
        retry_missing_s=retry_missing_s,
        # TTFT-critical: the re-probe cadence bounds how long a
        # just-published layer sits before its retry lands.
        retry_interval_s=retry_interval_s,
        fetch_gate=fetch_gate,
    )
    ids = np.asarray(block_ids)
    prefix_dev = jnp.asarray(ids, jnp.int32)
    state = {"out": list(caches), "fallback": False}
    installed = [False] * n_layers
    via_handle = [False] * n_layers
    install_tasks: List[Optional[asyncio.Task]] = [None] * n_layers

    async def _install(layer: int) -> None:
        if layer > 0:
            # install_layer must be called with strictly increasing layer
            # (staging regions wrap) — chain on the previous layer's task.
            await _layer_task(layer - 1)
        if installed[layer]:
            return
        if not state["fallback"]:
            out, ok = await handle.install_layer(state["out"], ids, layer)
            state["out"] = out
            if ok:
                installed[layer] = True
                via_handle[layer] = True
                if trace_events is not None:
                    trace_events.append(("install", layer))
                return
            # Late/failed layer: the handle is written off (install_layer
            # cancelled the rest) — recompute the WHOLE prefix locally.
            # Layers already installed used bitwise-identical bytes, so the
            # step's partial activation chain stays valid and the loop just
            # continues from this layer over recomputed caches.
            state["fallback"] = True
            ds.bump("disagg_fallback_recomputes")
            telemetry.get_journal().emit(
                "disagg_fallback", failed_layer=layer, prefix_blocks=n_blocks
            )
            if span is not None:
                span.annotate(disagg_fallback_layer=layer)
            state["out"] = _recompute_prefix(
                params, config, prompt, state["out"], prefix_dev
            )
        for l in range(n_layers):
            if not installed[l]:
                installed[l] = True
                if trace_events is not None:
                    trace_events.append(("install", l))

    def _layer_task(layer: int) -> asyncio.Task:
        # Memoized per-layer install: the install-ahead pipeline and the
        # compute loop both await the SAME task, so a layer installs once
        # no matter who reaches it first.
        if install_tasks[layer] is None:
            install_tasks[layer] = asyncio.ensure_future(_install(layer))
        return install_tasks[layer]

    async def ensure_layer(layer: int) -> None:
        await _layer_task(layer)

    # INSTALL-AHEAD: kick every layer's install now, in order. Installs
    # (device_put + scatter) then ride BEHIND the compute loop instead of
    # serializing in front of each layer's attention — the compute side
    # only waits when it genuinely outruns the transfer (a watermark
    # stall), which is the whole point of the overlap.
    for layer in range(n_layers):
        _layer_task(layer)
    for layer in range(wm):
        await ensure_layer(layer)
    # Launch instant: what is still in flight right now is the overlap the
    # watermark bought (the blocking path would have waited all of it out).
    inflight = [
        l for l in range(n_layers) if not installed[l] and not handle.layer_ready(l)
    ]
    ds.bump("disagg_inflight_at_first_token", len(inflight))
    tokens, first_logits, t_first = await _run_decode_steps(
        params, config, state, block_table, first_token, len(prompt),
        gen_tokens, len(block_table), ensure_layer=ensure_layer,
        trace_events=trace_events,
    )
    overlap = sum(1 for l in inflight if via_handle[l])
    ds.bump("disagg_overlap_layers", overlap)
    stalls = handle.retry_stalls + handle.wait_stalls
    ds.bump("disagg_watermark_stalls", stalls)
    if span is not None:
        span.annotate(
            disagg_overlap_layers=overlap, disagg_inflight=len(inflight),
            disagg_stalls=stalls,
        )
    return DecodeResult(
        tokens=tokens,
        first_logits=first_logits,
        caches=state["out"],
        t_first=t_first,
        fallback=state["fallback"],
        overlap_layers=overlap,
        inflight_at_first_token=len(inflight),
        watermark_stalls=stalls,
    )


async def local_decode(
    params,
    config: LlamaConfig,
    prompt: Sequence[int],
    caches,
    block_ids: np.ndarray,
    block_table: np.ndarray,
    first_token: int,
    *,
    gen_tokens: int = 1,
) -> DecodeResult:
    """The no-store baseline AND the byte oracle: recompute the prefix
    locally (same jitted chain as prefill/fallback), then run the same
    decode steps. A handoff decode that disagrees bitwise with this leg's
    ``first_logits`` moved wrong bytes."""
    state = {
        "out": _recompute_prefix(
            params, config, prompt, list(caches),
            jnp.asarray(np.asarray(block_ids), jnp.int32),
        ),
        "fallback": False,
    }
    tokens, first_logits, t_first = await _run_decode_steps(
        params, config, state, block_table, first_token, len(prompt),
        gen_tokens, len(block_table),
    )
    return DecodeResult(
        tokens=tokens, first_logits=first_logits, caches=state["out"],
        t_first=t_first, fallback=False, overlap_layers=0,
        inflight_at_first_token=0, watermark_stalls=0,
    )


# -- two-engine harness ------------------------------------------------------


class DisaggHarness:
    """Two-engine prefill→decode rig over one store.

    ``make_conn`` returns a fresh CONNECTED store connection; the harness
    builds one prefill-side and one decode-side :class:`KVConnector` on
    separate connections with separate block layouts (the decode engine
    never shares the prefill engine's physical blocks — only store keys).
    Legs (each returns ``{"ttft_s", "result", ...}``; TTFT is measured from
    the leg's request-arrival instant, before any compute or fetch):

    - :meth:`run_overlapped` — streamed prefill + watermark-gated decode,
      concurrently (the handoff under test).
    - :meth:`run_blocking` — same concurrency, ``watermark=n_layers``:
      today's blocking fetch-all.
    - :meth:`run_local` — no store; local layer-chunked recompute (also the
      byte oracle).
    - :meth:`run_cold` — sequential: full prefill durable FIRST, then a
      fetch-all decode (store-and-forward).

    For the chaos leg the prefill side runs as a REAL subprocess instead:
    ``python -m infinistore_tpu.disagg --role prefill ...`` (spawned via
    tools/fleet.py) against the same store, and :meth:`run_overlapped` is
    simply not given a prefill coroutine (``prefill=False``)."""

    def __init__(
        self,
        make_conn,
        config: Optional[LlamaConfig] = None,
        *,
        num_blocks: int = 32,
        req_blocks: int = 4,
        gen_blocks: int = 1,
        seed: int = 0,
        model_id: str = "disagg-demo",
        first_token: int = 42,
    ):
        self.config = config or demo_config()
        self.num_blocks = num_blocks
        self.req_blocks = req_blocks
        self.gen_blocks = gen_blocks
        self.first_token = first_token
        self.params = init_params(self.config, jax.random.PRNGKey(seed))
        spec = self.config.kv_spec(num_blocks)
        self.prefill_kv = KVConnector(
            make_conn(), spec, model_id, max_blocks=req_blocks
        )
        self.decode_kv = KVConnector(
            make_conn(), spec, model_id, max_blocks=req_blocks
        )

    def tables(self):
        """(prefill table, decode prefix ids, decode padded table row) —
        disjoint layouts so a byte match proves store transport, not shared
        memory."""
        n = self.req_blocks
        prefill_table = np.arange(n, dtype=np.int32)
        decode_ids = np.arange(n, dtype=np.int32) + n
        gen = np.arange(self.gen_blocks, dtype=np.int32) + 2 * n
        return prefill_table, decode_ids, np.concatenate([decode_ids, gen])

    def prompt(self, seed: int = 0, n_blocks: Optional[int] = None) -> List[int]:
        return demo_prompt(self.config, n_blocks or self.req_blocks, seed=seed)

    def heterogeneous_prompts(self, count: int, seed: int = 0) -> List[List[int]]:
        """Heterogeneous prompt lengths for the ragged decode-wave workload
        (block counts cycle 1..req_blocks): what the bench leg feeds the
        continuous-batching engine to report ``engine_wave_pad_fraction``
        under a disagg-shaped mix."""
        return [
            self.prompt(seed=seed + i, n_blocks=1 + i % self.req_blocks)
            for i in range(count)
        ]

    def trace_prompts(
        self, trace, count: Optional[int] = None
    ) -> List[List[int]]:
        """Materialize a loadgen :class:`~infinistore_tpu.loadgen.Trace`
        into prompts sized for THIS harness (docs/serving_load.md): token
        lists from the trace's own seed (shared family prefixes intact),
        clamped to ``req_blocks`` so every prompt fits the harness's
        per-request table. The trace-driven counterpart of
        :meth:`heterogeneous_prompts` — one workload definition grades
        the engine waves, the bench serving leg, AND the disagg handoff."""
        prompts = trace.prompts(
            self.config.block_tokens, vocab=self.config.vocab,
            max_blocks=self.req_blocks,
        )
        return prompts[:count] if count is not None else prompts

    def fresh_caches(self):
        return self.config.kv_spec(self.num_blocks).make_caches()

    def drop(self, prompt) -> int:
        """Drop the prompt's keys so the next round's fetch really races its
        ship (paired bench rounds must each start cold)."""
        return self.decode_kv.drop(prompt)

    async def _handoff(
        self, prompt, *, watermark: int, gen_tokens: int,
        retry_missing_s: float, prefill: bool = True, trace_events=None,
        sequential: bool = False,
    ):
        cfg = self.config
        prefill_table, decode_ids, row = self.tables()
        t0 = time.perf_counter()
        prefill_task = None
        fetch_gate = None
        written = 0
        if prefill and sequential:
            _, _, written = await stream_prefill(
                self.prefill_kv, self.params, cfg, prompt,
                self.fresh_caches(), prefill_table,
            )  # durable before the fetch starts
        elif prefill:
            # Announce-driven handoff: the prefill side signals each
            # layer's publication, the decode side's layer-l read waits
            # for it (no probe storm). The chaos subprocess path has no
            # in-proc channel and rides the retry loop instead.
            shipped = [asyncio.Event() for _ in range(cfg.n_layers)]
            prefill_task = asyncio.ensure_future(
                stream_prefill(
                    self.prefill_kv, self.params, cfg, prompt,
                    self.fresh_caches(), prefill_table,
                    on_layer_shipped=lambda layer: shipped[layer].set(),
                )
            )

            async def fetch_gate(layer, _ev=shipped):
                await _ev[layer].wait()
        res = await overlapped_decode(
            self.decode_kv, self.params, cfg, prompt, self.fresh_caches(),
            decode_ids, row, self.first_token, watermark=watermark,
            known_hit=len(decode_ids), retry_missing_s=retry_missing_s,
            fetch_gate=fetch_gate, gen_tokens=gen_tokens,
            trace_events=trace_events,
        )
        if prefill_task is not None:
            _, _, written = await prefill_task
        return {"ttft_s": res.t_first - t0, "result": res, "written": written}

    async def run_overlapped(
        self, prompt, *, watermark: int = 1, gen_tokens: int = 1,
        retry_missing_s: float = 10.0, prefill: bool = True, trace_events=None,
    ):
        return await self._handoff(
            prompt, watermark=watermark, gen_tokens=gen_tokens,
            retry_missing_s=retry_missing_s, prefill=prefill,
            trace_events=trace_events,
        )

    async def run_blocking(
        self, prompt, *, gen_tokens: int = 1, retry_missing_s: float = 10.0,
        prefill: bool = True,
    ):
        return await self._handoff(
            prompt, watermark=self.config.n_layers, gen_tokens=gen_tokens,
            retry_missing_s=retry_missing_s, prefill=prefill,
        )

    async def run_cold(self, prompt, *, gen_tokens: int = 1):
        return await self._handoff(
            prompt, watermark=self.config.n_layers, gen_tokens=gen_tokens,
            retry_missing_s=0.0, sequential=True,
        )

    async def run_proc(
        self, proc: "PrefillProcess", prompt_seed: int, *,
        watermark: int = 1, gen_tokens: int = 1, cold: bool = False,
        retry_missing_s: float = 10.0,
    ):
        """One handoff round against a REAL prefill subprocess (the bench's
        timing mode — prefill compute genuinely parallel with decode
        fetch+install, which a single event loop cannot give). TTFT is
        measured from the ``go`` send (request arrival at the prefill
        engine). ``cold=True`` is the store-and-forward leg: wait for the
        producer's ``done`` before fetching at all."""
        prompt = demo_prompt(self.config, self.req_blocks, seed=prompt_seed)
        _, decode_ids, row = self.tables()
        rnd = proc.start_round(prompt_seed)
        t0 = time.perf_counter()
        await proc.go(prompt_seed)
        if cold:
            await rnd.done
            res = await overlapped_decode(
                self.decode_kv, self.params, self.config, prompt,
                self.fresh_caches(), decode_ids, row, self.first_token,
                watermark=self.config.n_layers, known_hit=len(decode_ids),
                retry_missing_s=0.0, gen_tokens=gen_tokens,
            )
        else:
            async def gate(layer, _r=rnd):
                await _r.shipped[layer].wait()

            res = await overlapped_decode(
                self.decode_kv, self.params, self.config, prompt,
                self.fresh_caches(), decode_ids, row, self.first_token,
                watermark=watermark, known_hit=len(decode_ids),
                retry_missing_s=retry_missing_s, fetch_gate=gate,
                gen_tokens=gen_tokens,
            )
            await rnd.done
        return {"ttft_s": res.t_first - t0, "result": res, "written": rnd.written}

    async def run_local(self, prompt, *, gen_tokens: int = 1):
        _, decode_ids, row = self.tables()
        t0 = time.perf_counter()
        res = await local_decode(
            self.params, self.config, prompt, self.fresh_caches(),
            decode_ids, row, self.first_token, gen_tokens=gen_tokens,
        )
        return {"ttft_s": res.t_first - t0, "result": res, "written": 0}

    def check_bytes(self, got: DecodeResult, oracle: DecodeResult) -> bool:
        """Bitwise first-token verification against the local-recompute
        oracle; a mismatch is wrong bytes (counted, MUST stay 0)."""
        ok = bool(np.array_equal(got.first_logits, oracle.first_logits))
        if not ok:
            counters().bump("disagg_wrong_bytes")
        return ok


# -- subprocess prefill role -------------------------------------------------


def prefill_argv(
    port: int,
    *,
    serve: bool = False,
    blocks: int = 4,
    n_layers: int = 6,
    block_tokens: int = 8,
    dim: int = 64,
    ffn_dim: int = 128,
    pace_ms: float = 0.0,
    seed: int = 0,
    prompt_seed: int = 0,
    stall_after_layer: Optional[int] = None,
    stall_s: float = 0.0,
    crash_after_layers: Optional[int] = None,
    trace_id: Optional[int] = None,
) -> List[str]:
    """argv for a prefill-engine subprocess (the canonical builder —
    tools/fleet.py's spawn helper and :meth:`PrefillProcess.spawn` both use
    it, so every caller records the exact argv it launched)."""
    import sys

    argv = [
        sys.executable, "-m", "infinistore_tpu.disagg",
        "--port", str(port), "--role", "prefill",
        "--blocks", str(blocks), "--n-layers", str(n_layers),
        "--block-tokens", str(block_tokens),
        "--dim", str(dim), "--ffn-dim", str(ffn_dim),
        "--pace-ms", str(pace_ms),
        "--seed", str(seed), "--prompt-seed", str(prompt_seed),
    ]
    if serve:
        argv.append("--serve")
    if stall_after_layer is not None:
        argv += ["--stall-after-layer", str(stall_after_layer), "--stall-s", str(stall_s)]
    if crash_after_layers is not None:
        argv += ["--crash-after-layers", str(crash_after_layers)]
    if trace_id is not None:
        argv += ["--trace-id", str(trace_id)]
    return argv


@dataclasses.dataclass
class _PrefillRound:
    """One ``go``-round's announce state: per-layer publication events (the
    decode side's ``fetch_gate`` awaits these) and the done future."""

    shipped: List[asyncio.Event]
    done: asyncio.Future
    written: int = 0


class PrefillProcess:
    """The prefill ENGINE as a separate OS process (the two-engine shape a
    real disaggregated deployment has), driven over a line protocol:

      stdin:  ``go <prompt_seed>``  — prefill+stream that prompt's KV
              ``quit``              — exit
      stdout: ``ready``             — jax up, store connected
              ``shipped <seed> <layer>`` — layer's puts durable (the
              announce channel the decode side's fetch gate consumes)
              ``done <seed> <written>``  — all layers durable

    The announcement REPLACES store re-probing for the bench legs: the
    decode process's layer-``l`` read launches when ``shipped l`` arrives,
    never before — overlap without a probe storm. Spawn via
    :meth:`spawn` (async; the bench) or tools/fleet.py's
    ``spawn_disagg_prefill`` (sync Popen; the chaos test, which kill -9s
    the process mid-handoff instead of talking to it)."""

    def __init__(self, proc, n_layers: int):
        self.proc = proc
        self.n_layers = n_layers
        self._rounds: dict = {}
        self._reader: Optional[asyncio.Task] = None

    @classmethod
    async def spawn(
        cls, port: int, *, blocks: int = 4, n_layers: int = 6,
        block_tokens: int = 8, dim: int = 64, ffn_dim: int = 128,
        pace_ms: float = 0.0, seed: int = 0, ready_timeout_s: float = 180.0,
    ) -> "PrefillProcess":
        argv = prefill_argv(
            port, serve=True, blocks=blocks, n_layers=n_layers,
            block_tokens=block_tokens, dim=dim, ffn_dim=ffn_dim,
            pace_ms=pace_ms, seed=seed,
        )
        proc = await asyncio.create_subprocess_exec(
            *argv, stdin=asyncio.subprocess.PIPE, stdout=asyncio.subprocess.PIPE
        )
        self = cls(proc, n_layers)

        async def until_ready():
            while True:
                line = await proc.stdout.readline()
                if not line:
                    raise RuntimeError("prefill process exited before ready")
                if line.decode().strip() == "ready":
                    return

        await asyncio.wait_for(until_ready(), ready_timeout_s)
        self._reader = asyncio.ensure_future(self._read_loop())
        return self

    def start_round(self, prompt_seed: int) -> _PrefillRound:
        r = _PrefillRound(
            shipped=[asyncio.Event() for _ in range(self.n_layers)],
            done=asyncio.get_running_loop().create_future(),
        )
        self._rounds[prompt_seed] = r
        return r

    async def go(self, prompt_seed: int) -> None:
        self.proc.stdin.write(f"go {prompt_seed}\n".encode())
        await self.proc.stdin.drain()

    async def _read_loop(self) -> None:
        while True:
            line = await self.proc.stdout.readline()
            if not line:
                return
            parts = line.decode().split()
            if parts[:1] == ["shipped"] and len(parts) == 3:
                r = self._rounds.get(int(parts[1]))
                if r is not None:
                    r.shipped[int(parts[2])].set()
            elif parts[:1] == ["done"] and len(parts) == 3:
                r = self._rounds.get(int(parts[1]))
                if r is not None and not r.done.done():
                    r.written = int(parts[2])
                    r.done.set_result(r.written)

    async def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
        try:
            self.proc.stdin.write(b"quit\n")
            await self.proc.stdin.drain()
            await asyncio.wait_for(self.proc.wait(), 10.0)
        except Exception:
            self.proc.kill()
            await self.proc.wait()


def _main(argv=None) -> int:
    """``python -m infinistore_tpu.disagg``: the prefill engine as its own
    OS process (the shape a real disaggregated deployment has; the chaos
    test kill -9s this mid-handoff). Prints ``shipped layer N`` as each
    layer's puts become durable and ``prefill done wrote=...`` at the end —
    the spawn helper (tools/fleet.py) and the chaos test key off those
    markers."""
    ap = argparse.ArgumentParser(prog="python -m infinistore_tpu.disagg")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--role", choices=["prefill"], default="prefill")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--prompt-seed", type=int, default=0)
    ap.add_argument("--blocks", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=6)
    ap.add_argument("--block-tokens", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--ffn-dim", type=int, default=128)
    ap.add_argument("--pace-ms", type=float, default=0.0)
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--stall-after-layer", type=int, default=None)
    ap.add_argument("--stall-s", type=float, default=0.0)
    ap.add_argument("--crash-after-layers", type=int, default=None)
    ap.add_argument("--trace-id", type=int, default=None)
    args = ap.parse_args(argv)

    from .hostmesh import force_cpu_devices

    force_cpu_devices(1)
    import infinistore_tpu as its

    cfg = demo_config(
        n_layers=args.n_layers, block_tokens=args.block_tokens,
        dim=args.dim, ffn_dim=args.ffn_dim,
    )
    params = init_params(cfg, jax.random.PRNGKey(args.seed))
    prompt = demo_prompt(cfg, args.blocks, seed=args.prompt_seed)
    conn = its.InfinityConnection(
        its.ClientConfig(
            host_addr="127.0.0.1", service_port=args.port, log_level="error"
        )
    )
    conn.connect()
    kv = KVConnector(
        conn, cfg.kv_spec(args.blocks), "disagg-demo", max_blocks=args.blocks
    )
    table = np.arange(args.blocks, dtype=np.int32)

    async def run_one(pr, on_layer_shipped) -> int:
        span = None
        if args.trace_id is not None:
            # Cross-process trace continuation: the decode side's installs
            # and this side's ships share one trace id.
            span = tracing.Span("disagg.prefill", trace_id=args.trace_id)
        with tracing.use_span(span):
            _, _, written = await stream_prefill(
                kv, params, cfg, pr, cfg.kv_spec(args.blocks).make_caches(),
                table,
                on_layer_shipped=on_layer_shipped,
                stall_after_layer=args.stall_after_layer,
                stall_s=args.stall_s,
                crash_after_layers=args.crash_after_layers,
                pace_s=args.pace_ms / 1e3,
            )
        if span is not None:
            span.finish("ok")
        return written

    if args.serve:
        # PrefillProcess's line protocol: rounds on stdin, announcements
        # on stdout (class docstring).
        import sys

        async def serve() -> None:
            loop = asyncio.get_running_loop()
            print("ready", flush=True)
            while True:
                line = await loop.run_in_executor(None, sys.stdin.readline)
                parts = line.split()
                if not line or parts[:1] == ["quit"]:
                    return
                if parts[:1] != ["go"] or len(parts) != 2:
                    continue
                seed = int(parts[1])
                written = await run_one(
                    demo_prompt(cfg, args.blocks, seed=seed),
                    lambda layer, s=seed: print(
                        f"shipped {s} {layer}", flush=True
                    ),
                )
                print(f"done {seed} {written}", flush=True)

        asyncio.run(serve())
    else:
        written = asyncio.run(
            run_one(
                prompt,
                lambda layer: print(f"shipped layer {layer}", flush=True),
            )
        )
        print(f"prefill done wrote={written}", flush=True)
    conn.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
