"""Force an n-device virtual CPU backend for tests and sharding dryruns.

Must run before JAX initializes any backend: XLA flags are consumed once, at
first backend creation. Handles the axon TPU-tunnel sitecustomize, which
force-registers its single-chip plugin whenever PALLAS_AXON_POOL_IPS is set
and overrides JAX_PLATFORMS from the environment.
"""

import os


def force_cpu_devices(n_devices: int = 8) -> None:
    """Pin JAX to a CPU backend with ``n_devices`` virtual devices.

    Safe to call on any host: pops the axon tunnel env var, pins the platform
    list to cpu, and sets ``--xla_force_host_platform_device_count``. A
    caller-provided count >= ``n_devices`` is honored (e.g. running tests on
    a bigger virtual mesh); a smaller one can't satisfy the requirement and
    is replaced with a warning. No-op for the flag if backends are already
    initialized (too late to change — invoke before the first jax operation).
    """
    import re

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    m = re.search(r"--?xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        flags = (flags + " " + flag).strip()
    elif int(m.group(1)) < n_devices:
        import warnings

        warnings.warn(
            f"XLA_FLAGS forces {m.group(1)} host devices but {n_devices} are "
            f"required; overriding to {n_devices}"
        )
        flags = flags[: m.start()] + flag + flags[m.end():]
    os.environ["XLA_FLAGS"] = flags
    import jax

    # The axon plugin's register() runs jax.config.update("jax_platforms",
    # "axon,cpu") at interpreter start, which beats the env var.
    jax.config.update("jax_platforms", "cpu")
