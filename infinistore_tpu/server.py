"""Server CLI + HTTP management plane.

TPU-native rebuild of the reference's infinistore/server.py (argparse
:42-148, periodic evict task :157-186, OOM-score protection :151-154, FastAPI
manage port :25-39, uvloop startup :173-198). Differences:

- The data plane is the native epoll reactor (its own thread), so there is no
  uvloop grafting; plain asyncio runs the control plane.
- The manage HTTP server is a dependency-free asyncio implementation (this
  environment has no fastapi/uvicorn) serving the same endpoints — POST /purge
  and GET /kvmap_len — plus GET /selftest, which the reference README
  advertises but never implemented (doc/code discrepancy noted in SURVEY.md
  §5.5), and GET /stats and GET /usage for the per-op counters.
- Flags are generated from the ServerConfig dataclass: one source of truth
  instead of the reference's four-place duplication rule (config.h:7-12).

Run: python -m infinistore_tpu.server --service-port 22345 --manage-port 28080
"""

import argparse
import asyncio
import dataclasses
import json
import signal
import sys

from . import lib as _lib
from .config import ServerConfig
from .lib import Logger, register_server, unregister_server

_SKIP_CLI = {"extra"}


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="infinistore-tpu",
        description="TPU-native distributed KV-cache store server",
    )
    for f in dataclasses.fields(ServerConfig):
        if f.name in _SKIP_CLI:
            continue
        flag = "--" + f.name.replace("_", "-")
        if f.type == "bool" or isinstance(f.default, bool):
            parser.add_argument(
                flag,
                action=argparse.BooleanOptionalAction,
                default=f.default,
                help=f"(default: {f.default})",
            )
        else:
            parser.add_argument(
                flag,
                type=type(f.default),
                default=f.default,
                help=f"(default: {f.default})",
            )
    return parser


def parse_args(argv=None) -> ServerConfig:
    args = vars(build_parser().parse_args(argv))
    return ServerConfig(**args)


def prevent_oom() -> None:
    """Protect the cache process from the kernel OOM killer (reference
    server.py:151-154 writes oom_score_adj=-1000)."""
    try:
        with open("/proc/self/oom_score_adj", "w") as f:
            f.write("-1000")
    except (OSError, PermissionError) as e:
        Logger.warn(f"cannot set oom_score_adj (need privileges): {e}")


# ---------------------------------------------------------------------------
# Minimal HTTP management server (stdlib asyncio; no fastapi/uvicorn here).
# ---------------------------------------------------------------------------


def _http_response(status: int, payload: dict) -> bytes:
    body = json.dumps(payload).encode()
    reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed", 500: "Error"}.get(
        status, "OK"
    )
    return (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + body


def _prometheus_text(stats: dict) -> bytes:
    """Render the stats snapshot in Prometheus exposition format (the
    reference exposes no metrics at all — SURVEY.md §5.1/§5.5)."""
    lines = [
        "# TYPE infinistore_kvmap_entries gauge",
        f"infinistore_kvmap_entries {stats['kvmap_len']}",
        "# TYPE infinistore_pool_usage_ratio gauge",
        f"infinistore_pool_usage_ratio {stats['usage']:.6f}",
        "# TYPE infinistore_pool_bytes gauge",
        f'infinistore_pool_bytes{{kind="total"}} {stats["total_bytes"]}',
        f'infinistore_pool_bytes{{kind="used"}} {stats["used_bytes"]}',
        "# TYPE infinistore_connections gauge",
        f"infinistore_connections {stats['connections']}",
        "# TYPE infinistore_connections_accepted counter",
        f"infinistore_connections_accepted {stats['conns_accepted']}",
        "# TYPE infinistore_pools gauge",
        f"infinistore_pools {stats['pools']}",
        "# TYPE infinistore_pool_pinned gauge",
        f"infinistore_pool_pinned {1 if stats['pinned'] else 0}",
    ]
    spill = stats.get("spill", {})
    if spill.get("capacity", 0) > 0:
        lines += [
            "# TYPE infinistore_spill_bytes gauge",
            f'infinistore_spill_bytes{{kind="used"}} {spill["bytes"]}',
            f'infinistore_spill_bytes{{kind="capacity"}} {spill["capacity"]}',
            "# TYPE infinistore_spill_entries gauge",
            f"infinistore_spill_entries {spill['entries']}",
            "# TYPE infinistore_spill_promotions counter",
            f"infinistore_spill_promotions {spill['promotions']}",
            "# TYPE infinistore_spill_dropped counter",
            f"infinistore_spill_dropped {spill['dropped']}",
        ]
    # Data-plane queue depth + two-class QoS scheduler counters
    # (docs/qos.md): suspended sliced ops by class, per-class dispatch and
    # slice counts, and the scheduler's preempt/age decisions.
    qos = stats.get("qos")
    if qos is not None:
        lines += [
            "# TYPE infinistore_dataplane_suspended_ops gauge",
            f"infinistore_dataplane_suspended_ops {stats.get('suspended_ops', 0)}",
            "# TYPE infinistore_qos_queued gauge",
            f'infinistore_qos_queued{{class="fg"}} {qos["fg_queued"]}',
            f'infinistore_qos_queued{{class="bg"}} {qos["bg_queued"]}',
            "# TYPE infinistore_qos_ops counter",
            f'infinistore_qos_ops{{class="fg"}} {qos["fg_ops"]}',
            f'infinistore_qos_ops{{class="bg"}} {qos["bg_ops"]}',
            "# TYPE infinistore_qos_slices counter",
            f'infinistore_qos_slices{{class="fg"}} {qos["fg_slices"]}',
            f'infinistore_qos_slices{{class="bg"}} {qos["bg_slices"]}',
            "# TYPE infinistore_qos_bg_preempted_slices counter",
            f"infinistore_qos_bg_preempted_slices {qos['bg_preempted_slices']}",
            "# TYPE infinistore_qos_bg_aged_slices counter",
            f"infinistore_qos_bg_aged_slices {qos['bg_aged_slices']}",
            # Scheduler tunables as gauges: config drift across a fleet is
            # an operational incident dashboards should be able to show.
            "# TYPE infinistore_qos_bg_cooldown_us gauge",
            f"infinistore_qos_bg_cooldown_us {qos['bg_cooldown_us']}",
            "# TYPE infinistore_qos_bg_aging_us gauge",
            f"infinistore_qos_bg_aging_us {qos['bg_aging_us']}",
        ]
    # Exposition format requires all samples of a family in one uninterrupted
    # group after its TYPE line — one pass per family, not per op.
    ops = sorted(stats.get("ops", {}).items())
    lines.append("# TYPE infinistore_op_count counter")
    for op, s in ops:
        lines.append(f'infinistore_op_count{{op="{op}",result="ok"}} '
                     f'{s["count"] - s["errors"]}')
        lines.append(f'infinistore_op_count{{op="{op}",result="error"}} {s["errors"]}')
    lines.append("# TYPE infinistore_op_bytes counter")
    for op, s in ops:
        lines.append(f'infinistore_op_bytes{{op="{op}",dir="in"}} {s["bytes_in"]}')
        lines.append(f'infinistore_op_bytes{{op="{op}",dir="out"}} {s["bytes_out"]}')
    lines.append("# TYPE infinistore_op_time_us counter")
    for op, s in ops:
        lines.append(f'infinistore_op_time_us{{op="{op}"}} {s["total_us"]}')
    lines.append("# TYPE infinistore_op_p50_latency_us gauge")
    for op, s in ops:
        lines.append(f'infinistore_op_p50_latency_us{{op="{op}"}} {s["p50_us"]}')
    # p99 is the number the QoS gates regression-check (tools/bench_check.py)
    # — exporting only p50 hid tail inflation from dashboards (ITS-C001).
    lines.append("# TYPE infinistore_op_p99_latency_us gauge")
    for op, s in ops:
        lines.append(f'infinistore_op_p99_latency_us{{op="{op}"}} {s["p99_us"]}')
    body = ("\n".join(lines) + "\n").encode()
    return (
        f"HTTP/1.1 200 OK\r\n"
        f"Content-Type: text/plain; version=0.0.4\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + body


class ManageServer:
    """The management plane: /purge, /kvmap_len (reference server.py:25-39),
    /selftest (advertised in reference README.md:56-57 but missing), /stats,
    /usage, /metrics (Prometheus), /health."""

    def __init__(self, config: ServerConfig):
        self.config = config
        self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                writer.close()
                return
            method, path = parts[0], parts[1]
            # Drain headers.
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
            resp = await self._route(method, path)
            writer.write(resp)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _route(self, method: str, path: str) -> bytes:
        path = path.split("?", 1)[0]
        try:
            if path == "/purge" and method == "POST":
                count = await asyncio.to_thread(_lib.purge_kv_map)
                return _http_response(200, {"status": "ok", "count": count})
            if path == "/kvmap_len" and method == "GET":
                n = await asyncio.to_thread(_lib.get_kvmap_len)
                return _http_response(200, {"len": n})
            if path == "/stats" and method == "GET":
                stats = await asyncio.to_thread(_lib.get_server_stats)
                return _http_response(200, stats)
            if path == "/usage" and method == "GET":
                stats = await asyncio.to_thread(_lib.get_server_stats)
                return _http_response(200, {"usage": stats["usage"]})
            if path == "/metrics" and method == "GET":
                stats = await asyncio.to_thread(_lib.get_server_stats)
                return _prometheus_text(stats)
            if path == "/health" and method == "GET":
                return _http_response(200, {"status": "ok"})
            if path == "/selftest" and method == "GET":
                return _http_response(200, await asyncio.to_thread(self._selftest))
            if path in ("/purge", "/kvmap_len", "/stats", "/usage", "/metrics",
                        "/selftest", "/health"):
                return _http_response(405, {"error": "method not allowed"})
            return _http_response(404, {"error": "not found"})
        except Exception as e:  # control plane must not die on a bad request
            Logger.error(f"manage request {method} {path} failed: {e}")
            return _http_response(500, {"error": str(e)})

    def _selftest(self) -> dict:
        """Loopback write/read/delete through the real data plane."""
        import numpy as np

        from .lib import ClientConfig, InfinityConnection

        key = "__selftest__"
        conn = InfinityConnection(
            ClientConfig(
                host_addr="127.0.0.1",
                service_port=self.config.service_port,
                log_level="error",
            )
        )
        try:
            conn.connect()
            data = np.arange(4096, dtype=np.uint8)
            conn.tcp_write_cache(key, data.ctypes.data, data.nbytes)
            back = conn.tcp_read_cache(key)
            ok = bool(np.array_equal(back, data))
            conn.delete_keys([key])
            return {"status": "ok" if ok else "corrupt", "roundtrip_bytes": int(data.nbytes)}
        finally:
            conn.close()

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, host=self.config.host, port=self.config.manage_port
        )
        Logger.info(f"manage plane on {self.config.host}:{self.config.manage_port}")

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


async def periodic_evict(config: ServerConfig):
    """Background eviction loop (reference server.py:157-186)."""
    while True:
        await asyncio.sleep(config.evict_interval)
        try:
            evicted = await asyncio.to_thread(
                _lib.evict_cache, config.evict_min_threshold, config.evict_max_threshold
            )
            if evicted:
                Logger.info(f"periodic evict: {evicted} entries")
        except Exception as e:
            Logger.error(f"periodic evict failed: {e}")


async def serve(config: ServerConfig) -> None:
    register_server(None, config)
    # /proc write = file IO; keep it off the event loop (ITS-L002).
    await asyncio.to_thread(prevent_oom)
    manage = ManageServer(config)
    await manage.start()
    tasks = []
    if config.evict_enabled:
        tasks.append(asyncio.create_task(periodic_evict(config)))

    stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop_event.set)
    Logger.info(f"infinistore-tpu serving on {config.host}:{config.service_port}")
    try:
        await stop_event.wait()
    finally:
        for t in tasks:
            t.cancel()
        await manage.stop()
        unregister_server()


def main(argv=None) -> int:
    config = parse_args(argv)
    config.verify()
    Logger.set_log_level(config.log_level)
    try:
        asyncio.run(serve(config))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
