"""Server CLI + HTTP management plane.

TPU-native rebuild of the reference's infinistore/server.py (argparse
:42-148, periodic evict task :157-186, OOM-score protection :151-154, FastAPI
manage port :25-39, uvloop startup :173-198). Differences:

- The data plane is the native epoll reactor (its own thread), so there is no
  uvloop grafting; plain asyncio runs the control plane.
- The manage HTTP server is a dependency-free asyncio implementation (this
  environment has no fastapi/uvicorn) serving the same endpoints — POST /purge
  and GET /kvmap_len — plus GET /selftest, which the reference README
  advertises but never implemented (doc/code discrepancy noted in SURVEY.md
  §5.5), and GET /stats and GET /usage for the per-op counters.
- Flags are generated from the ServerConfig dataclass: one source of truth
  instead of the reference's four-place duplication rule (config.h:7-12).

Run: python -m infinistore_tpu.server --service-port 22345 --manage-port 28080
"""

import argparse
import asyncio
import dataclasses
import json
import math
import os
import signal
import sys
import threading
import urllib.parse

from . import lib as _lib
from . import profiling, telemetry, tracing
from .config import ServerConfig
from .lib import Logger, register_server, unregister_server

_SKIP_CLI = {"extra"}


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="infinistore-tpu",
        description="TPU-native distributed KV-cache store server",
    )
    for f in dataclasses.fields(ServerConfig):
        if f.name in _SKIP_CLI:
            continue
        flag = "--" + f.name.replace("_", "-")
        if f.type == "bool" or isinstance(f.default, bool):
            parser.add_argument(
                flag,
                action=argparse.BooleanOptionalAction,
                default=f.default,
                help=f"(default: {f.default})",
            )
        else:
            parser.add_argument(
                flag,
                type=type(f.default),
                default=f.default,
                help=f"(default: {f.default})",
            )
    return parser


def parse_args(argv=None) -> ServerConfig:
    args = vars(build_parser().parse_args(argv))
    return ServerConfig(**args)


def prevent_oom() -> None:
    """Protect the cache process from the kernel OOM killer (reference
    server.py:151-154 writes oom_score_adj=-1000)."""
    try:
        with open("/proc/self/oom_score_adj", "w") as f:
            f.write("-1000")
    except (OSError, PermissionError) as e:
        Logger.warn(f"cannot set oom_score_adj (need privileges): {e}")


# ---------------------------------------------------------------------------
# Minimal HTTP management server (stdlib asyncio; no fastapi/uvicorn here).
# ---------------------------------------------------------------------------


def _http_response(status: int, payload: dict) -> bytes:
    body = json.dumps(payload).encode()
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              405: "Method Not Allowed", 500: "Error"}.get(status, "OK")
    return (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + body


def _text_response(status: int, text: str,
                   ctype: str = "text/plain; charset=utf-8") -> bytes:
    """Non-JSON response (the folded-stack /profile body)."""
    body = text.encode()
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found"}.get(status, "OK")
    return (
        f"HTTP/1.1 {status} {reason}\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + body


def _prometheus_text(stats: dict, membership_status: dict = None,
                     slo_status: dict = None, event_counts: dict = None,
                     gossip_status: dict = None, tier_status: dict = None,
                     prof_status: dict = None, timeseries_status: dict = None,
                     disagg_status: dict = None,
                     engine_wave_status: dict = None,
                     exemplars: bool = False) -> bytes:
    """Render the stats snapshot in Prometheus exposition format (the
    reference exposes no metrics at all — SURVEY.md §5.1/§5.5). With a
    cluster attached to the manage plane, ``membership_status`` appends
    the membership/reshard gauge families (docs/membership.md);
    ``slo_status``/``event_counts`` append the fleet-telemetry families
    (docs/observability.md). ``exemplars`` (``GET /metrics?exemplars=1``)
    attaches OpenMetrics exemplars — the trace id of the slowest recorded
    op per histogram — to the matching ``_bucket`` line; the default
    output stays plain Prometheus, byte-identical to pre-exemplar."""
    lines = [
        "# TYPE infinistore_kvmap_entries gauge",
        f"infinistore_kvmap_entries {stats['kvmap_len']}",
        "# TYPE infinistore_pool_usage_ratio gauge",
        f"infinistore_pool_usage_ratio {stats['usage']:.6f}",
        "# TYPE infinistore_pool_bytes gauge",
        f'infinistore_pool_bytes{{kind="total"}} {stats["total_bytes"]}',
        f'infinistore_pool_bytes{{kind="used"}} {stats["used_bytes"]}',
        "# TYPE infinistore_connections gauge",
        f"infinistore_connections {stats['connections']}",
        "# TYPE infinistore_connections_accepted counter",
        f"infinistore_connections_accepted {stats['conns_accepted']}",
        "# TYPE infinistore_pools gauge",
        f"infinistore_pools {stats['pools']}",
        "# TYPE infinistore_pool_pinned gauge",
        f"infinistore_pool_pinned {1 if stats['pinned'] else 0}",
    ]
    spill = stats.get("spill", {})
    if spill.get("capacity", 0) > 0:
        lines += [
            "# TYPE infinistore_spill_bytes gauge",
            f'infinistore_spill_bytes{{kind="used"}} {spill["bytes"]}',
            f'infinistore_spill_bytes{{kind="capacity"}} {spill["capacity"]}',
            "# TYPE infinistore_spill_entries gauge",
            f"infinistore_spill_entries {spill['entries']}",
            "# TYPE infinistore_spill_promotions counter",
            f"infinistore_spill_promotions {spill['promotions']}",
            "# TYPE infinistore_spill_dropped counter",
            f"infinistore_spill_dropped {spill['dropped']}",
        ]
    # Data-plane queue depth + two-class QoS scheduler counters
    # (docs/qos.md): suspended sliced ops by class, per-class dispatch and
    # slice counts, and the scheduler's preempt/age decisions.
    qos = stats.get("qos")
    if qos is not None:
        lines += [
            "# TYPE infinistore_dataplane_suspended_ops gauge",
            f"infinistore_dataplane_suspended_ops {stats.get('suspended_ops', 0)}",
            "# TYPE infinistore_qos_queued gauge",
            f'infinistore_qos_queued{{class="fg"}} {qos["fg_queued"]}',
            f'infinistore_qos_queued{{class="bg"}} {qos["bg_queued"]}',
            "# TYPE infinistore_qos_ops counter",
            f'infinistore_qos_ops{{class="fg"}} {qos["fg_ops"]}',
            f'infinistore_qos_ops{{class="bg"}} {qos["bg_ops"]}',
            "# TYPE infinistore_qos_slices counter",
            f'infinistore_qos_slices{{class="fg"}} {qos["fg_slices"]}',
            f'infinistore_qos_slices{{class="bg"}} {qos["bg_slices"]}',
            "# TYPE infinistore_qos_bg_preempted_slices counter",
            f"infinistore_qos_bg_preempted_slices {qos['bg_preempted_slices']}",
            "# TYPE infinistore_qos_bg_aged_slices counter",
            f"infinistore_qos_bg_aged_slices {qos['bg_aged_slices']}",
            # Scheduler tunables as gauges: config drift across a fleet is
            # an operational incident dashboards should be able to show.
            "# TYPE infinistore_qos_bg_cooldown_us gauge",
            f"infinistore_qos_bg_cooldown_us {qos['bg_cooldown_us']}",
            "# TYPE infinistore_qos_bg_aging_us gauge",
            f"infinistore_qos_bg_aging_us {qos['bg_aging_us']}",
        ]
    # Descriptor-ring data plane (docs/descriptor_ring.md): attach/consume/
    # complete lifetime counters, the doorbell-vs-descriptor coalescing
    # ratio (one doorbell per doze, not per op), live ring depths, and the
    # two rejection classes (bad = per-descriptor 400 CQE, torn =
    # generation-tag mismatch, fatal for the connection).
    ring = stats.get("ring")
    if ring is not None:
        lines += [
            "# TYPE infinistore_ring_conns gauge",
            f"infinistore_ring_conns {ring['conns']}",
            "# TYPE infinistore_ring_attached counter",
            f"infinistore_ring_attached {ring['attached']}",
            "# TYPE infinistore_ring_descriptors counter",
            f"infinistore_ring_descriptors {ring['descriptors']}",
            "# TYPE infinistore_ring_doorbells counter",
            f'infinistore_ring_doorbells{{dir="rx"}} {ring["doorbells_rx"]}',
            f'infinistore_ring_doorbells{{dir="tx"}} {ring["cq_doorbells_tx"]}',
            "# TYPE infinistore_ring_completions counter",
            f"infinistore_ring_completions {ring['completions']}",
            "# TYPE infinistore_ring_bad_descriptors counter",
            f"infinistore_ring_bad_descriptors {ring['bad_descriptors']}",
            "# TYPE infinistore_ring_torn_descriptors counter",
            f"infinistore_ring_torn_descriptors {ring['torn_descriptors']}",
            "# TYPE infinistore_ring_sq_depth gauge",
            f"infinistore_ring_sq_depth {ring['sq_depth']}",
            "# TYPE infinistore_ring_pending gauge",
            f"infinistore_ring_pending {ring['pending']}",
            # PR 16 mechanism counters: multi-op batch slots (one slot per
            # coalesced flush) and the adaptive poll-then-park windows —
            # hits completed without parking, arms fell back to the epoll
            # doze, elided doorbells found the client already awake.
            "# TYPE infinistore_ring_batch_slots counter",
            f"infinistore_ring_batch_slots {ring['batch_slots']}",
            "# TYPE infinistore_ring_batch_ops counter",
            f"infinistore_ring_batch_ops {ring['batch_ops']}",
            "# TYPE infinistore_ring_poll_hits counter",
            f"infinistore_ring_poll_hits {ring['poll_hits']}",
            "# TYPE infinistore_ring_poll_arms counter",
            f"infinistore_ring_poll_arms {ring['poll_arms']}",
            "# TYPE infinistore_ring_doorbell_elided counter",
            f"infinistore_ring_doorbell_elided {ring['doorbell_elided']}",
        ]
    # Reactor loop-pass phase accounting (docs/observability.md,
    # profiling section): per-phase cumulative microseconds plus the pass
    # count — rate() over infinistore_prof_loop_us gives per-phase
    # utilization, the native denominator under the /profile sampler's
    # Python-side frames.
    nprof = stats.get("prof")
    if nprof is not None:
        lines += [
            "# TYPE infinistore_prof_loop_passes counter",
            f"infinistore_prof_loop_passes {nprof['passes']}",
            "# TYPE infinistore_prof_loop_us counter",
            f'infinistore_prof_loop_us{{phase="wait"}} {nprof["wait_us"]}',
            f'infinistore_prof_loop_us{{phase="events"}} {nprof["events_us"]}',
            f'infinistore_prof_loop_us{{phase="rings"}} {nprof["rings_us"]}',
            f'infinistore_prof_loop_us{{phase="slices"}} {nprof["slices_us"]}',
            f'infinistore_prof_loop_us{{phase="poll"}} {nprof["poll_us"]}',
            f'infinistore_prof_loop_us{{phase="other"}} {nprof["other_us"]}',
        ]
    # Tracing surfaces (docs/observability.md): the client flight
    # recorder's counters (span volume + the slow-op watchdog) and the
    # server-side trace tick ring's coverage counters. The spans/ticks
    # themselves are served by GET /trace, not scraped.
    rec = tracing.recorder()
    tr = stats.get("trace", {})
    lines += [
        "# TYPE infinistore_trace_slow_ops_total counter",
        f"infinistore_trace_slow_ops_total {rec.slow_ops_total if rec else 0}",
        "# TYPE infinistore_trace_spans_recorded counter",
        f"infinistore_trace_spans_recorded {rec.recorded if rec else 0}",
        "# TYPE infinistore_trace_spans_dropped counter",
        f"infinistore_trace_spans_dropped {rec.dropped if rec else 0}",
        "# TYPE infinistore_trace_server_ticks_recorded counter",
        f"infinistore_trace_server_ticks_recorded {tr.get('recorded', 0)}",
        "# TYPE infinistore_trace_server_ticks_dropped counter",
        f"infinistore_trace_server_ticks_dropped {tr.get('dropped', 0)}",
    ]
    # Exposition format requires all samples of a family in one uninterrupted
    # group after its TYPE line — one pass per family, not per op.
    ops = sorted(stats.get("ops", {}).items())
    lines.append("# TYPE infinistore_op_count counter")
    for op, s in ops:
        lines.append(f'infinistore_op_count{{op="{op}",result="ok"}} '
                     f'{s["count"] - s["errors"]}')
        lines.append(f'infinistore_op_count{{op="{op}",result="error"}} {s["errors"]}')
    lines.append("# TYPE infinistore_op_bytes counter")
    for op, s in ops:
        lines.append(f'infinistore_op_bytes{{op="{op}",dir="in"}} {s["bytes_in"]}')
        lines.append(f'infinistore_op_bytes{{op="{op}",dir="out"}} {s["bytes_out"]}')
    lines.append("# TYPE infinistore_op_time_us counter")
    for op, s in ops:
        lines.append(f'infinistore_op_time_us{{op="{op}"}} {s["total_us"]}')
    # Proper log-bucketed latency HISTOGRAM per op (base-2 octaves, 32
    # sub-buckets = ~2% resolution — native OpStats::lat_buckets, exported
    # sparse as [le_us, count]): dashboards can aggregate/re-quantile it,
    # which the old p99 point-gauges could not. The cumulative `le` walk +
    # +Inf/_sum/_count triplet is the Prometheus histogram contract.
    # Exemplar sources (``?exemplars=1``, OpenMetrics syntax): the slowest
    # recorded trace-tick per op, so a p99 bucket links its trace id
    # straight into the flight recorder (`GET /trace`). Off by default —
    # the plain exposition bytes are unchanged.
    slowest: dict = {}
    if exemplars:
        tick_entries = tr.get("entries", [])
        for e in tick_entries:
            dur = e.get("done_us", 0) - e.get("recv_us", 0)
            if e.get("trace_id") and dur > 0:
                cur = slowest.get(e.get("op"))
                if cur is None or dur > cur[0]:
                    slowest[e.get("op")] = (dur, e.get("trace_id"))
    lines.append("# TYPE infinistore_op_duration_us histogram")
    for op, s in ops:
        cum = 0
        ex = slowest.get(op)
        for le, cnt in s.get("hist_us", []):
            cum += cnt
            line = f'infinistore_op_duration_us_bucket{{op="{op}",le="{le}"}} {cum}'
            if ex is not None and ex[0] <= le:
                line += f' # {{trace_id="{ex[1]:#x}"}} {float(ex[0])}'
                ex = None
            lines.append(line)
        inf_line = (
            f'infinistore_op_duration_us_bucket{{op="{op}",le="+Inf"}} {s["count"]}'
        )
        if ex is not None:
            inf_line += f' # {{trace_id="{ex[1]:#x}"}} {float(ex[0])}'
        lines.append(inf_line)
        lines.append(f'infinistore_op_duration_us_sum{{op="{op}"}} {s["total_us"]}')
        lines.append(f'infinistore_op_duration_us_count{{op="{op}"}} {s["count"]}')
    # p50/p99 stay as DERIVED gauges (computed natively from the same
    # buckets) so existing dashboards and the bench_check gates keep their
    # names; the histogram above is the primary surface.
    lines.append("# TYPE infinistore_op_p50_latency_us gauge")
    for op, s in ops:
        lines.append(f'infinistore_op_p50_latency_us{{op="{op}"}} {s["p50_us"]}')
    # p99 is the number the QoS gates regression-check (tools/bench_check.py)
    # — exporting only p50 hid tail inflation from dashboards (ITS-C001).
    lines.append("# TYPE infinistore_op_p99_latency_us gauge")
    for op, s in ops:
        lines.append(f'infinistore_op_p99_latency_us{{op="{op}"}} {s["p99_us"]}')
    if membership_status is not None:
        lines += _membership_prometheus_lines(membership_status)
    if gossip_status is not None:
        lines += _gossip_prometheus_lines(gossip_status)
    if tier_status is not None:
        lines += _tier_prometheus_lines(tier_status)
    if disagg_status is not None:
        lines += _disagg_prometheus_lines(disagg_status)
    if engine_wave_status is not None:
        lines += _engine_wave_prometheus_lines(engine_wave_status)
    if slo_status is not None:
        lines += _slo_prometheus_lines(slo_status)
    if prof_status is not None:
        lines += _prof_prometheus_lines(prof_status)
    if timeseries_status is not None:
        lines += _timeseries_prometheus_lines(timeseries_status)
    if event_counts is not None:
        lines += _events_prometheus_lines(event_counts)
    # Exemplar syntax is ILLEGAL in the plain 0.0.4 text format (a scraper
    # parsing it there rejects the whole body) — the exemplar variant must
    # declare OpenMetrics, whose parser requires the trailing # EOF. That
    # parser also enforces counter naming: the family is declared by BASE
    # name and samples carry ``_total``. The legacy counter vocabulary
    # predates that rule, so here (and only here) the TYPE lines adapt:
    # ``foo_total``-named families are declared by base (samples already
    # conform), anything else is declared ``unknown``, which OpenMetrics
    # accepts with any name. Exemplars ride only the histogram ``_bucket``
    # lines, where they are legal; sample names/values stay identical to
    # the plain rendering.
    if exemplars:
        def _om_type(ln: str) -> str:
            if not (ln.startswith("# TYPE ") and ln.endswith(" counter")):
                return ln
            family = ln.split(" ")[2]
            if family.endswith("_total"):
                return f"# TYPE {family[: -len('_total')]} counter"
            return ln[: -len("counter")] + "unknown"

        lines = [_om_type(ln) for ln in lines]
        lines.append("# EOF")
        ctype = "application/openmetrics-text; version=1.0.0; charset=utf-8"
    else:
        ctype = "text/plain; version=0.0.4"
    body = ("\n".join(lines) + "\n").encode()
    return (
        f"HTTP/1.1 200 OK\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\n"
        f"Connection: close\r\n\r\n"
    ).encode() + body


def _membership_prometheus_lines(ms: dict) -> list:
    """Membership + reshard gauges for /metrics, from the flat
    ``ClusterKVConnector.membership_status()`` snapshot (the same dict the
    ``/membership`` endpoint serves; key vocabulary in
    ``Membership.status`` / ``Resharder.progress``). The counters checker
    (ITS-C005, tools/analysis/counters.py) cross-checks that every status
    key is consumed here — a membership counter that never reaches a
    dashboard is observability drift."""
    return [
        "# TYPE infinistore_membership_epoch gauge",
        f"infinistore_membership_epoch {ms['membership_epoch']}",
        "# TYPE infinistore_membership_epoch_changes counter",
        f"infinistore_membership_epoch_changes {ms['membership_epoch_changes']}",
        "# TYPE infinistore_membership_members gauge",
        f"infinistore_membership_members {ms['membership_members']}",
        "# TYPE infinistore_membership_state gauge",
        f'infinistore_membership_state{{state="joining"}} {ms["membership_joining"]}',
        f'infinistore_membership_state{{state="active"}} {ms["membership_active"]}',
        f'infinistore_membership_state{{state="leaving"}} {ms["membership_leaving"]}',
        f'infinistore_membership_state{{state="dead"}} {ms["membership_dead"]}',
        f'infinistore_membership_state{{state="removed"}} {ms["membership_removed"]}',
        "# TYPE infinistore_membership_settled gauge",
        f"infinistore_membership_settled {ms['membership_settled']}",
        "# TYPE infinistore_reshard_active gauge",
        f"infinistore_reshard_active {ms['reshard_active']}",
        "# TYPE infinistore_reshard_passes counter",
        f"infinistore_reshard_passes {ms['reshard_passes']}",
        "# TYPE infinistore_reshard_replans counter",
        f"infinistore_reshard_replans {ms['reshard_replans']}",
        "# TYPE infinistore_reshard_planned_roots counter",
        f"infinistore_reshard_planned_roots {ms['reshard_planned_roots']}",
        "# TYPE infinistore_reshard_moved_roots counter",
        f"infinistore_reshard_moved_roots {ms['reshard_moved_roots']}",
        "# TYPE infinistore_reshard_moved_keys counter",
        f"infinistore_reshard_moved_keys {ms['reshard_moved_keys']}",
        "# TYPE infinistore_reshard_moved_bytes counter",
        f"infinistore_reshard_moved_bytes {ms['reshard_moved_bytes']}",
        "# TYPE infinistore_reshard_pruned_keys counter",
        f"infinistore_reshard_pruned_keys {ms['reshard_pruned_keys']}",
        "# TYPE infinistore_reshard_skipped_keys counter",
        f"infinistore_reshard_skipped_keys {ms['reshard_skipped_keys']}",
        "# TYPE infinistore_reshard_failed_roots counter",
        f"infinistore_reshard_failed_roots {ms['reshard_failed_roots']}",
        "# TYPE infinistore_reshard_lost_roots counter",
        f"infinistore_reshard_lost_roots {ms['reshard_lost_roots']}",
        "# TYPE infinistore_reshard_debt_roots gauge",
        f"infinistore_reshard_debt_roots {ms['reshard_debt_roots']}",
        "# TYPE infinistore_reshard_prune_debt gauge",
        f"infinistore_reshard_prune_debt {ms['reshard_prune_debt']}",
        "# TYPE infinistore_reshard_last_pass_ms gauge",
        f"infinistore_reshard_last_pass_ms {ms['reshard_last_pass_ms']}",
        "# TYPE infinistore_reshard_catalog_roots gauge",
        f"infinistore_reshard_catalog_roots {ms.get('reshard_catalog_roots', 0)}",
        # Durable catalog + reshard journal (docs/membership.md, durability
        # section): append/fsync/compaction volume plus what the last
        # startup replay saw (torn tails discarded, checksum-bad records
        # skipped). Zeros when the cluster runs without a journal.
        "# TYPE infinistore_journal_records counter",
        f"infinistore_journal_records {ms.get('journal_records', 0)}",
        "# TYPE infinistore_journal_bytes gauge",
        f"infinistore_journal_bytes {ms.get('journal_bytes', 0)}",
        "# TYPE infinistore_journal_fsyncs counter",
        f"infinistore_journal_fsyncs {ms.get('journal_fsyncs', 0)}",
        "# TYPE infinistore_journal_compactions counter",
        f"infinistore_journal_compactions {ms.get('journal_compactions', 0)}",
        "# TYPE infinistore_journal_replay_records gauge",
        f"infinistore_journal_replay_records {ms.get('journal_replay_records', 0)}",
        "# TYPE infinistore_journal_replay_torn gauge",
        f"infinistore_journal_replay_torn {ms.get('journal_replay_torn', 0)}",
        "# TYPE infinistore_journal_replay_bad_checksum gauge",
        f"infinistore_journal_replay_bad_checksum "
        f"{ms.get('journal_replay_bad_checksum', 0)}",
    ]


def _gossip_prometheus_lines(gs: dict) -> list:
    """Gossip anti-entropy gauge families for /metrics, from the flat
    ``telemetry.GossipAgent.status`` snapshot. The counters checker
    (ITS-C006) holds this exporter to the ``gossip_*`` status vocabulary
    both ways (docs/membership.md, gossip section)."""
    return [
        "# TYPE infinistore_gossip_peers gauge",
        f"infinistore_gossip_peers {gs['gossip_peers']}",
        "# TYPE infinistore_gossip_rounds counter",
        f"infinistore_gossip_rounds {gs['gossip_rounds']}",
        "# TYPE infinistore_gossip_exchanges counter",
        f"infinistore_gossip_exchanges {gs['gossip_exchanges']}",
        "# TYPE infinistore_gossip_exchange_failures counter",
        f"infinistore_gossip_exchange_failures {gs['gossip_exchange_failures']}",
        "# TYPE infinistore_gossip_merges counter",
        f'infinistore_gossip_merges{{dir="in"}} {gs["gossip_merges_in"]}',
        f'infinistore_gossip_merges{{dir="out"}} {gs["gossip_merges_out"]}',
        "# TYPE infinistore_gossip_last_epoch_seen gauge",
        f"infinistore_gossip_last_epoch_seen {gs['gossip_last_epoch_seen']}",
        "# TYPE infinistore_gossip_last_round_ms gauge",
        f"infinistore_gossip_last_round_ms {gs['gossip_last_round_ms']}",
    ]


def _tier_prometheus_lines(ts: dict) -> list:
    """Tiered-capacity-plane gauge families for /metrics, from the flat
    ``tiering.TierManager.status`` snapshot (the same dict ``GET /tiers``
    serves). The counters checker (ITS-C007, tools/analysis/counters.py)
    holds this exporter to the ``tier_*`` status vocabulary both ways —
    a tier the dashboards cannot see is observability drift
    (docs/tiering.md)."""
    return [
        "# TYPE infinistore_tier_cold_members gauge",
        f"infinistore_tier_cold_members {ts['tier_cold_members']}",
        "# TYPE infinistore_tier_cold_roots gauge",
        f"infinistore_tier_cold_roots {ts['tier_cold_roots']}",
        "# TYPE infinistore_tier_tracked_roots gauge",
        f"infinistore_tier_tracked_roots {ts['tier_tracked_roots']}",
        "# TYPE infinistore_tier_sketch_evictions counter",
        f"infinistore_tier_sketch_evictions {ts['tier_sketch_evictions']}",
        "# TYPE infinistore_tier_hits counter",
        f'infinistore_tier_hits{{tier="ram"}} {ts["tier_ram_hits"]}',
        f'infinistore_tier_hits{{tier="cold"}} {ts["tier_cold_hits"]}',
        f'infinistore_tier_hits{{tier="demotion"}} {ts["tier_demotion_hits"]}',
        "# TYPE infinistore_tier_misses counter",
        f"infinistore_tier_misses {ts['tier_misses']}",
        "# TYPE infinistore_tier_cold_reads counter",
        f"infinistore_tier_cold_reads {ts['tier_cold_reads']}",
        "# TYPE infinistore_tier_cold_read_p99_us gauge",
        f"infinistore_tier_cold_read_p99_us {ts['tier_cold_read_p99_us']}",
        "# TYPE infinistore_tier_demotions counter",
        f"infinistore_tier_demotions {ts['tier_demotions']}",
        "# TYPE infinistore_tier_demoted_keys counter",
        f"infinistore_tier_demoted_keys {ts['tier_demoted_keys']}",
        "# TYPE infinistore_tier_demoted_bytes counter",
        f"infinistore_tier_demoted_bytes {ts['tier_demoted_bytes']}",
        "# TYPE infinistore_tier_demote_failures counter",
        f"infinistore_tier_demote_failures {ts['tier_demote_failures']}",
        "# TYPE infinistore_tier_promotions counter",
        f"infinistore_tier_promotions {ts['tier_promotions']}",
        "# TYPE infinistore_tier_promoted_keys counter",
        f"infinistore_tier_promoted_keys {ts['tier_promoted_keys']}",
        "# TYPE infinistore_tier_promoted_bytes counter",
        f"infinistore_tier_promoted_bytes {ts['tier_promoted_bytes']}",
        "# TYPE infinistore_tier_promote_failures counter",
        f"infinistore_tier_promote_failures {ts['tier_promote_failures']}",
        "# TYPE infinistore_tier_admit_rejects counter",
        f"infinistore_tier_admit_rejects {ts['tier_admit_rejects']}",
        "# TYPE infinistore_tier_direct_reads counter",
        f"infinistore_tier_direct_reads {ts['tier_direct_reads']}",
        "# TYPE infinistore_tier_promote_backlog gauge",
        f"infinistore_tier_promote_backlog {ts['tier_promote_backlog']}",
        "# TYPE infinistore_tier_demote_backlog gauge",
        f"infinistore_tier_demote_backlog {ts['tier_demote_backlog']}",
        "# TYPE infinistore_tier_wrong_reads counter",
        f"infinistore_tier_wrong_reads {ts['tier_wrong_reads']}",
        "# TYPE infinistore_tier_last_pass_ms gauge",
        f"infinistore_tier_last_pass_ms {ts['tier_last_pass_ms']}",
    ]


def _disagg_prometheus_lines(ds: dict) -> list:
    """Disaggregated-handoff counter families for /metrics, from the flat
    ``disagg.DisaggCounters.status`` snapshot (the same dict ``GET
    /disagg`` serves). The counters checker (ITS-C009,
    tools/analysis/counters.py) holds this exporter to the ``disagg_*``
    ledger vocabulary both ways — a handoff counter the dashboards cannot
    see is observability drift (docs/disaggregation.md)."""
    return [
        "# TYPE infinistore_disagg_handoffs counter",
        f"infinistore_disagg_handoffs {ds['disagg_handoffs']}",
        "# TYPE infinistore_disagg_overlap_layers counter",
        f"infinistore_disagg_overlap_layers {ds['disagg_overlap_layers']}",
        "# TYPE infinistore_disagg_inflight_at_first_token counter",
        "infinistore_disagg_inflight_at_first_token "
        f"{ds['disagg_inflight_at_first_token']}",
        "# TYPE infinistore_disagg_watermark_stalls counter",
        f"infinistore_disagg_watermark_stalls {ds['disagg_watermark_stalls']}",
        "# TYPE infinistore_disagg_fallback_recomputes counter",
        "infinistore_disagg_fallback_recomputes "
        f"{ds['disagg_fallback_recomputes']}",
        "# TYPE infinistore_disagg_wrong_bytes counter",
        f"infinistore_disagg_wrong_bytes {ds['disagg_wrong_bytes']}",
    ]


def _disagg_status():
    """The process-wide disagg counter snapshot, or None when no handoff
    has run here. Lazy on purpose: ``infinistore_tpu.disagg`` pulls in
    the jax engine stack, and the core client/server API must stay
    importable without it — so this only *observes* an already-imported
    module (``sys.modules``), never imports one."""
    dsd = sys.modules.get("infinistore_tpu.disagg")
    if dsd is None:
        return None
    return dsd.counters().status()


def _engine_wave_prometheus_lines(ws: dict) -> list:
    """Skew-aware wave-policy counter families for /metrics, from the flat
    ``engine.WaveCounters.status`` snapshot (the same dict ``GET /wave``
    serves). The counters checker (ITS-C010, tools/analysis/counters.py)
    holds this exporter to the ``engine_wave_*`` ledger vocabulary both
    ways — a deferral the dashboards cannot see is observability drift
    (docs/serving_load.md)."""
    return [
        "# TYPE infinistore_engine_wave_deferrals counter",
        f"infinistore_engine_wave_deferrals {ws['engine_wave_deferrals']}",
        "# TYPE infinistore_engine_wave_aging_escapes counter",
        "infinistore_engine_wave_aging_escapes "
        f"{ws['engine_wave_aging_escapes']}",
        "# TYPE infinistore_engine_wave_held_flushes counter",
        f"infinistore_engine_wave_held_flushes {ws['engine_wave_held_flushes']}",
        "# TYPE infinistore_engine_wave_policy_waves counter",
        f"infinistore_engine_wave_policy_waves {ws['engine_wave_policy_waves']}",
        "# TYPE infinistore_engine_wave_defer_age_us_p99 gauge",
        "infinistore_engine_wave_defer_age_us_p99 "
        f"{ws['engine_wave_defer_age_us_p99']}",
        "# TYPE infinistore_engine_wave_bucket_occupancy gauge",
        "infinistore_engine_wave_bucket_occupancy "
        f"{ws['engine_wave_bucket_occupancy']}",
    ]


def _engine_wave_status():
    """The process-wide wave-policy counter snapshot, or None when no
    engine has run here. Lazy on purpose (same discipline as
    ``_disagg_status``): ``infinistore_tpu.engine`` pulls in the jax
    stack, and the core client/server API must stay importable without
    it — so this only *observes* an already-imported module
    (``sys.modules``), never imports one."""
    eng = sys.modules.get("infinistore_tpu.engine")
    if eng is None:
        return None
    return eng.wave_counters().status()


def _prof_prometheus_lines(ps: dict) -> list:
    """Sampling-profiler gauge families for /metrics, from the flat
    ``profiling.SamplingProfiler.status`` snapshot. The counters checker
    (ITS-C008, tools/analysis/counters.py) holds this exporter to the
    ``prof_*`` status vocabulary both ways — a profiler whose coverage
    dashboards cannot see is observability drift
    (docs/observability.md, profiling section)."""
    return [
        "# TYPE infinistore_prof_samples counter",
        f"infinistore_prof_samples {ps['prof_samples']}",
        "# TYPE infinistore_prof_tagged_samples counter",
        f"infinistore_prof_tagged_samples {ps['prof_tagged_samples']}",
        "# TYPE infinistore_prof_threads gauge",
        f"infinistore_prof_threads {ps['prof_threads']}",
        "# TYPE infinistore_prof_buckets gauge",
        f"infinistore_prof_buckets {ps['prof_buckets']}",
        "# TYPE infinistore_prof_bucket_drops counter",
        f"infinistore_prof_bucket_drops {ps['prof_bucket_drops']}",
        "# TYPE infinistore_prof_pending gauge",
        f"infinistore_prof_pending {ps['prof_pending']}",
        "# TYPE infinistore_prof_pending_drops counter",
        f"infinistore_prof_pending_drops {ps['prof_pending_drops']}",
        "# TYPE infinistore_prof_snapshots gauge",
        f"infinistore_prof_snapshots {ps['prof_snapshots']}",
        "# TYPE infinistore_prof_hz gauge",
        f"infinistore_prof_hz {ps['prof_hz']}",
        "# TYPE infinistore_prof_ticks counter",
        f"infinistore_prof_ticks {ps['prof_ticks']}",
        "# TYPE infinistore_prof_tick_us counter",
        f"infinistore_prof_tick_us {ps['prof_tick_us']}",
    ]


def _timeseries_prometheus_lines(ts: dict) -> list:
    """Metrics-history gauge families for /metrics, from the flat
    ``telemetry.MetricsHistory.status`` snapshot (the same dict
    ``GET /timeseries`` serves alongside the series index). Held to the
    ``timeseries_*`` vocabulary both ways by ITS-C008
    (docs/observability.md, time-series section)."""
    return [
        "# TYPE infinistore_timeseries_series gauge",
        f"infinistore_timeseries_series {ts['timeseries_series']}",
        "# TYPE infinistore_timeseries_points gauge",
        f"infinistore_timeseries_points {ts['timeseries_points']}",
        "# TYPE infinistore_timeseries_samples counter",
        f"infinistore_timeseries_samples {ts['timeseries_samples']}",
        "# TYPE infinistore_timeseries_sources gauge",
        f"infinistore_timeseries_sources {ts['timeseries_sources']}",
        "# TYPE infinistore_timeseries_source_failures counter",
        f"infinistore_timeseries_source_failures {ts['timeseries_source_failures']}",
        "# TYPE infinistore_timeseries_dropped_series counter",
        f"infinistore_timeseries_dropped_series {ts['timeseries_dropped_series']}",
        "# TYPE infinistore_timeseries_anomalies counter",
        f"infinistore_timeseries_anomalies {ts['timeseries_anomalies']}",
        "# TYPE infinistore_timeseries_interval_s gauge",
        f"infinistore_timeseries_interval_s {ts['timeseries_interval_s']}",
        "# TYPE infinistore_timeseries_capacity gauge",
        f"infinistore_timeseries_capacity {ts['timeseries_capacity']}",
        "# TYPE infinistore_timeseries_last_pass_ms gauge",
        f"infinistore_timeseries_last_pass_ms {ts['timeseries_last_pass_ms']}",
    ]


def _slo_prometheus_lines(slo: dict) -> list:
    """SLO gauge families for /metrics, from the flat ``SloEngine.status``
    snapshot (the same dict ``GET /slo`` serves). The counters checker
    (ITS-C006, tools/analysis/counters.py) holds this exporter to the
    ``slo_*`` status vocabulary — an SLI dashboards cannot see is
    observability drift (docs/observability.md)."""
    lines = [
        "# TYPE infinistore_slo_availability gauge",
        f"infinistore_slo_availability {slo['slo_availability']}",
        "# TYPE infinistore_slo_fg_p99_us gauge",
        f"infinistore_slo_fg_p99_us {slo['slo_fg_p99_us']}",
        "# TYPE infinistore_slo_cold_p99_us gauge",
        f"infinistore_slo_cold_p99_us {slo['slo_cold_p99_us']}",
        "# TYPE infinistore_slo_miss_rate gauge",
        f"infinistore_slo_miss_rate {slo['slo_miss_rate']}",
        "# TYPE infinistore_slo_reshard_drain gauge",
        f"infinistore_slo_reshard_drain {slo['slo_reshard_drain']}",
        "# TYPE infinistore_slo_burn_rate_max gauge",
        f"infinistore_slo_burn_rate_max {slo['slo_burn_rate_max']}",
        "# TYPE infinistore_slo_alerts_firing gauge",
        f"infinistore_slo_alerts_firing {slo['slo_alerts_firing']}",
        "# TYPE infinistore_slo_alerts_total counter",
        f"infinistore_slo_alerts_total {slo['slo_alerts_total']}",
        "# TYPE infinistore_slo_burn_rate gauge",
    ]
    for name, detail in sorted(slo.get("objectives", {}).items()):
        for window, burn in sorted(detail.get("burn_rates", {}).items()):
            lines.append(
                f'infinistore_slo_burn_rate{{objective="{name}",'
                f'window="{window}"}} {burn}'
            )
    return lines


def _events_prometheus_lines(counts: dict) -> list:
    """Per-kind event-journal emit totals (``EventJournal.counts``); the
    full vocabulary is enumerated so a kind that never fired still scrapes
    as an explicit 0 (rate() needs the zero points)."""
    lines = ["# TYPE infinistore_events_total counter"]
    for kind in telemetry.EVENT_KINDS:
        lines.append(
            f'infinistore_events_total{{kind="{kind}"}} {counts.get(kind, 0)}'
        )
    return lines


def _trace_payload(stats: dict, fmt: str = "json",
                   member_spans: dict = None) -> bytes:
    """GET /trace body: recent spans from the process flight recorder
    joined with the local server's trace tick ring (``stats["trace"]``).

    ``fmt="json"`` (default) returns the span/tick dump plus the stage
    schema (``tracing.STAGES`` — the vocabulary the ITS-T checker holds
    producers and docs to); ``fmt="chrome"`` returns Chrome trace-event
    format — save the body to a file and load it in Perfetto
    (https://ui.perfetto.dev) or chrome://tracing (docs/observability.md).

    ``member_spans`` (``?scope=cluster`` with a fleet scraper attached):
    per-member scraped span sets to merge with the local recorder by
    trace id onto one timeline — a striped/replicated/reshard op that
    fanned out across processes renders as ONE tree, with one Perfetto
    track lane per member in the chrome format.

    Either way the payload cross-links the event journal: every journal
    event carrying a trace id present in the dump rides along in
    ``events``, so "why was this op slow" (breaker trip? epoch bump? QoS
    storm?) is answerable from one response."""
    trace = stats.get("trace", {})
    server_spans = tracing.server_tick_spans(trace)
    rec = tracing.recorder()
    client_spans = rec.snapshot() if rec is not None else []
    scope = "local" if member_spans is None else "cluster"
    if member_spans is not None:
        merged = telemetry.cluster_spans(
            client_spans + server_spans, member_spans
        )
    else:
        merged = client_spans + server_spans
    events = telemetry.get_journal().for_trace(
        {s.get("trace_id", 0) for s in merged} - {0}
    )
    if fmt == "chrome":
        payload = {
            "traceEvents": (
                telemetry.cluster_chrome_events(merged)
                if member_spans is not None
                else tracing.chrome_trace_events(merged)
            ),
            "displayTimeUnit": "ms",
        }
        return _http_response(200, payload)
    if member_spans is not None:
        return _http_response(200, {
            "enabled": tracing.enabled(),
            "scope": scope,
            "stages": list(tracing.STAGES),
            "spans": merged,
            "members": ["local", *member_spans.keys()],
            "events": events,
        })
    return _http_response(200, {
        "enabled": tracing.enabled(),
        "scope": scope,
        "stages": list(tracing.STAGES),
        "spans": client_spans,
        "server_spans": server_spans,
        "events": events,
        "slow_ops": rec.slow_snapshot() if rec is not None else [],
        "slow_ops_total": rec.slow_ops_total if rec is not None else 0,
        "recorded": rec.recorded if rec is not None else 0,
        "dropped": rec.dropped if rec is not None else 0,
        "server_recorded": trace.get("recorded", 0),
        "server_dropped": trace.get("dropped", 0),
    })


class ManageServer:
    """The management plane: /purge, /kvmap_len (reference server.py:25-39),
    /selftest (advertised in reference README.md:56-57 but missing), /stats,
    /usage, /metrics (Prometheus), /health (SLO-verdict-aware), /trace (the
    op-tracing dump; ?scope=cluster joins the fleet, docs/observability.md),
    /slo (burn-rate verdict) and /events (the causal event journal) — plus,
    with a cluster attached, /membership GET/POST (the elastic-membership
    control surface, docs/membership.md) and /tiers (the tiered capacity
    plane's tier_* counter snapshot, docs/tiering.md).

    ``cluster``: an optional ``ClusterKVConnector``-shaped object (needs
    ``membership`` / ``resharder`` / ``membership_status()`` / ``health()``
    and the add/remove/mark_dead transitions). A plain store server runs
    without one; a pool operator embeds the manage plane next to the
    cluster client to drive membership over HTTP. Connections the manage
    plane itself creates (POST add) are OWNED here: once their member
    reaches a terminal state (REMOVED after a drain, DEAD after a crash)
    they are closed on the next control-plane request — HTTP-driven
    join/leave churn never accumulates native connections."""

    def __init__(self, config: ServerConfig, cluster=None, scraper=None,
                 gossip=None, history=None):
        self.config = config
        self.cluster = cluster
        # Metrics history (docs/observability.md, time-series section): an
        # attached ``telemetry.MetricsHistory`` lights up ``GET
        # /timeseries`` (sparkline/trend queries) and its
        # ``infinistore_timeseries_*`` /metrics families. ``GET /profile``
        # needs no attachment — it serves the process-wide sampling
        # profiler (``profiling.profiler()``), which exists whenever
        # profiling was enabled.
        self.history = history
        # Fleet telemetry (docs/observability.md): an attached
        # ``telemetry.FleetScraper`` lights up ``GET /trace?scope=cluster``
        # (cluster-joined traces) and the per-member rows of ``GET /slo``.
        # ``/slo`` and ``/events`` themselves serve the process-wide SLO
        # engine and event journal and need no scraper.
        self.scraper = scraper
        # Crash-safe coordination (docs/membership.md): an attached
        # ``telemetry.GossipAgent`` adds its ``infinistore_gossip_*``
        # families to /metrics. The ``POST /gossip`` + ``GET /bootstrap``
        # routes need only the cluster — a peer can exchange views with a
        # process that runs no agent of its own.
        self.gossip = gossip
        self._server = None
        # member_id -> InfinityConnection this manage plane connected
        # (POST add); swept once the member goes terminal. Guarded: the
        # add runs on an executor thread (_add_member_blocking) while a
        # concurrent /membership request sweeps on the event loop —
        # unguarded, the insert can race the pop (ITS-R001).
        # its: guard[_owned_conns: _conns_lock]
        self._conns_lock = threading.Lock()
        self._owned_conns = {}

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=10)
            parts = request_line.decode("latin-1").split()
            if len(parts) < 2:
                writer.close()
                return
            method, path = parts[0], parts[1]
            # Drain headers, keeping Content-Length (POST bodies).
            content_len = 0
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=10)
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                if name.strip().lower() == "content-length":
                    try:
                        # Clamp both ways: a negative length must not reach
                        # readexactly().
                        content_len = max(0, min(int(value.strip()), 1 << 20))
                    except ValueError:
                        content_len = 0
            body = b""
            if content_len:
                body = await asyncio.wait_for(
                    reader.readexactly(content_len), timeout=10
                )
            resp = await self._route(method, path, body)
            writer.write(resp)
            await writer.drain()
        except (asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _route(self, method: str, path: str, body: bytes = b"") -> bytes:
        path, _, query = path.partition("?")
        try:
            if path == "/purge" and method == "POST":
                count = await asyncio.to_thread(_lib.purge_kv_map)
                return _http_response(200, {"status": "ok", "count": count})
            if path == "/kvmap_len" and method == "GET":
                n = await asyncio.to_thread(_lib.get_kvmap_len)
                return _http_response(200, {"len": n})
            if path == "/stats" and method == "GET":
                stats = await asyncio.to_thread(_lib.get_server_stats)
                return _http_response(200, stats)
            if path == "/usage" and method == "GET":
                stats = await asyncio.to_thread(_lib.get_server_stats)
                return _http_response(200, {"usage": stats["usage"]})
            if path == "/metrics" and method == "GET":
                ms = (
                    self.cluster.membership_status()
                    if self.cluster is not None else None
                )
                gs = self.gossip.status() if self.gossip is not None else None
                ts = (
                    self.cluster.tiering.status()
                    if self.cluster is not None
                    and getattr(self.cluster, "tiering", None) is not None
                    else None
                )
                params = urllib.parse.parse_qs(query)
                slo = telemetry.slo_engine().status()
                counts = telemetry.get_journal().counts()
                prof = profiling.profiler()
                ps = prof.status() if prof is not None else None
                hs = (
                    self.history.status()
                    if self.history is not None else None
                )
                ds = _disagg_status()
                ws = _engine_wave_status()
                try:
                    stats = await asyncio.to_thread(_lib.get_server_stats)
                except Exception:
                    # A cluster-side manage plane may run with no local
                    # store server in-process: membership + telemetry
                    # gauges must still scrape. A plain store server's
                    # failure stays a 500.
                    if ms is None:
                        raise
                    lines = (
                        _membership_prometheus_lines(ms)
                        + (_gossip_prometheus_lines(gs) if gs is not None else [])
                        + (_tier_prometheus_lines(ts) if ts is not None else [])
                        + (_disagg_prometheus_lines(ds) if ds is not None else [])
                        + (_engine_wave_prometheus_lines(ws)
                           if ws is not None else [])
                        + _slo_prometheus_lines(slo)
                        + (_prof_prometheus_lines(ps) if ps is not None else [])
                        + (_timeseries_prometheus_lines(hs)
                           if hs is not None else [])
                        + _events_prometheus_lines(counts)
                    )
                    body = ("\n".join(lines) + "\n").encode()
                    return (
                        f"HTTP/1.1 200 OK\r\n"
                        f"Content-Type: text/plain; version=0.0.4\r\n"
                        f"Content-Length: {len(body)}\r\n"
                        f"Connection: close\r\n\r\n"
                    ).encode() + body
                return _prometheus_text(
                    stats, membership_status=ms, slo_status=slo,
                    event_counts=counts, gossip_status=gs, tier_status=ts,
                    prof_status=ps, timeseries_status=hs, disagg_status=ds,
                    engine_wave_status=ws,
                    exemplars=params.get("exemplars") == ["1"],
                )
            if path == "/health" and method == "GET":
                # The health verdict CONSUMES the SLO engine: a fleet whose
                # error budget is burning is degraded even though this
                # process answers (docs/observability.md).
                slo = telemetry.slo_engine().status()
                return _http_response(200, {
                    "status": "ok" if slo["verdict"] == "ok" else "degraded",
                    "slo_verdict": slo["verdict"],
                    "slo_alerts_firing": slo["slo_alerts_firing"],
                })
            if path == "/slo" and method == "GET":
                # The SLO verdict endpoint: rolling SLIs, per-window burn
                # rates, firing alerts — plus the fleet scraper's
                # per-member health when one is attached.
                payload = telemetry.slo_engine().status()
                if self.scraper is not None:
                    payload["scraper"] = self.scraper.status()
                return _http_response(200, payload)
            if path == "/events" and method == "GET":
                # The causal event journal (?since_seq=N&limit=N): breaker
                # transitions, epoch bumps, quarantines, slow ops, QoS
                # storms, SLO alert edges — each with member/epoch/trace id.
                params = urllib.parse.parse_qs(query)
                try:
                    since = int(params.get("since_seq", ["0"])[0])
                    limit = int(params.get("limit", ["0"])[0]) or None
                except ValueError:
                    return _http_response(400, {"error": "bad since_seq/limit"})
                journal = telemetry.get_journal()
                return _http_response(200, {
                    "events": journal.snapshot(since_seq=since, limit=limit),
                    "counts": journal.counts(),
                    "emitted": journal.emitted,
                    "capacity": journal.capacity,
                })
            if path == "/trace" and method == "GET":
                # Recent op spans (flight recorder + native tick ring):
                # default JSON dump, ?fmt=chrome for Perfetto. A manage
                # plane with no local store still serves the client spans.
                # ?scope=cluster (fleet scraper attached): refresh the
                # scrape OFF-loop and merge every member's spans with the
                # local recorder by trace id — one timeline, one Perfetto
                # lane per member.
                try:
                    stats = await asyncio.to_thread(_lib.get_server_stats)
                except Exception:
                    stats = {}
                params = urllib.parse.parse_qs(query)
                fmt = "chrome" if params.get("fmt") == ["chrome"] else "json"
                member_spans = None
                if (
                    params.get("scope") == ["cluster"]
                    and self.scraper is not None
                ):
                    await asyncio.to_thread(self.scraper.scrape_once)
                    member_spans = self.scraper.member_spans()
                return _trace_payload(stats, fmt, member_spans=member_spans)
            if path == "/profile" and method == "GET":
                # The continuous sampling profiler (docs/observability.md,
                # profiling section): folded-stack text by default (any
                # flamegraph tool; the stage is the root frame), ?fmt=chrome
                # for a Perfetto sampling track on the same CLOCK_MONOTONIC
                # timeline as /trace, ?save=<name> to store a diff base,
                # ?diff=<name> for a differential profile against one.
                # Off-loop: the read side force-resolves pending samples.
                return await self._profile_get(query)
            if path == "/timeseries" and method == "GET":
                # The metrics history (docs/observability.md, time-series
                # section): no params = the series index + timeseries_*
                # status; ?metric=<series>&window=<seconds> = the points.
                return await self._timeseries_get(query)
            if path == "/selftest" and method == "GET":
                return _http_response(200, await asyncio.to_thread(self._selftest))
            if path == "/tiers" and method == "GET":
                # Tiered capacity plane (docs/tiering.md): the flat
                # tier_* counter snapshot — the TierManager.status
                # vocabulary /metrics exports as infinistore_tier_*
                # (ITS-C007) — plus each cold member's breaker row.
                tiering = (
                    getattr(self.cluster, "tiering", None)
                    if self.cluster is not None else None
                )
                if tiering is None:
                    return _http_response(
                        200, {"enabled": False, "error": "no tiering attached"}
                    )
                return _http_response(200, {
                    "enabled": True,
                    **tiering.status(),
                    "cold_members": [
                        {"member_id": mid, **h.as_dict()}
                        for mid, h in zip(
                            self.cluster.cold_ids, self.cluster._cold_health
                        )
                    ],
                })
            if path == "/disagg" and method == "GET":
                # Disaggregated prefill->decode handoff (docs/
                # disaggregation.md): the flat disagg_* counter snapshot —
                # the DisaggCounters.status vocabulary /metrics exports as
                # infinistore_disagg_* (ITS-C009). Served only when a
                # handoff has run in this process; the module stays
                # unimported (and jax unloaded) otherwise.
                ds = _disagg_status()
                if ds is None:
                    return _http_response(
                        200, {"enabled": False, "error": "no handoff has run"}
                    )
                return _http_response(200, {"enabled": True, **ds})
            if path == "/wave" and method == "GET":
                # Skew-aware wave flush policy (docs/serving_load.md): the
                # flat engine_wave_* counter snapshot — the
                # engine.WaveCounters.status vocabulary /metrics exports as
                # infinistore_engine_wave_* (ITS-C010). Served only when an
                # engine has run in this process; the module stays
                # unimported (and jax unloaded) otherwise.
                ws = _engine_wave_status()
                if ws is None:
                    return _http_response(
                        200, {"enabled": False, "error": "no engine has run"}
                    )
                return _http_response(200, {"enabled": True, **ws})
            if path == "/membership" and method == "GET":
                return self._membership_get()
            if path == "/membership" and method == "POST":
                return await self._membership_post(body)
            if path == "/gossip" and method == "POST":
                return await self._gossip_post(body)
            if path == "/bootstrap" and method == "GET":
                return await self._bootstrap_get(query)
            if path in ("/purge", "/kvmap_len", "/stats", "/usage", "/metrics",
                        "/selftest", "/health", "/trace", "/membership",
                        "/slo", "/events", "/gossip", "/bootstrap", "/tiers",
                        "/profile", "/timeseries", "/disagg", "/wave"):
                return _http_response(405, {"error": "method not allowed"})
            return _http_response(404, {"error": "not found"})
        except Exception as e:  # control plane must not die on a bad request
            Logger.error(f"manage request {method} {path} failed: {e}")
            return _http_response(500, {"error": str(e)})

    # -- elastic membership control surface (docs/membership.md) -------------

    def _sweep_owned_conns(self):
        """Close manage-plane-owned connections whose member went terminal
        (REMOVED after a drain completes, DEAD after a crash). Lazy: runs
        on each /membership request, so a leave's connection lives exactly
        until its drain finalizes."""
        if self.cluster is None or not self._owned_conns:  # its: allow[ITS-R001]
            return
        from .membership import MemberState

        view = self.cluster.membership.view()
        doomed = []
        # Audited bare read above: an empty-check racing an insert only
        # defers the sweep to the next request. The pop itself is guarded.
        # Audited lock-on-loop: O(members) dict scan + pop, no I/O — the
        # blocking close() runs after release (same discipline as the
        # cluster's _cat_lock sites).
        with self._conns_lock:  # its: allow[ITS-L003]
            for mid in list(self._owned_conns):
                if view.state_of(mid) in MemberState.TERMINAL:
                    doomed.append(self._owned_conns.pop(mid))
        for conn in doomed:
            try:
                conn.close()
            except Exception:
                pass

    async def _profile_get(self, query: str) -> bytes:
        """GET /profile: the process sampling profiler's aggregate.

        Default: folded-stack text (``stage;frame;...;leaf count``) —
        pipe into flamegraph.pl / speedscope / Perfetto's folded importer
        for per-stage flames. ``?fmt=chrome``: Chrome trace-event JSON —
        a sampling track on the same monotonic timeline as ``GET
        /trace``, so spans and stacks line up when both files load in
        one Perfetto session. ``?save=<name>`` stores the current
        aggregate as a named diff base (bounded); ``?diff=<name>``
        returns the differential profile against it. 200 with
        ``enabled: false`` when profiling was never configured (the
        /tiers discipline); reads run off-loop — the read side
        force-resolves pending samples."""
        prof = profiling.profiler()
        if prof is None:
            return _http_response(200, {
                "enabled": False,
                "error": "profiling off (INFINISTORE_TPU_PROFILE=1 or "
                         "profiling.configure(enabled=True))",
            })
        params = urllib.parse.parse_qs(query)
        save = params.get("save", [None])[0]
        diff = params.get("diff", [None])[0]
        if save:
            saved = await asyncio.to_thread(prof.snapshot_save, save)
            return _http_response(200, {
                "enabled": profiling.enabled(), "saved": saved,
                "snapshots": prof.snapshot_names(),
            })
        if diff:
            delta = await asyncio.to_thread(prof.diff, diff)
            if delta is None:
                return _http_response(404, {
                    "error": f"no saved snapshot {diff!r}",
                    "snapshots": prof.snapshot_names(),
                })
            return _http_response(200, {
                "enabled": profiling.enabled(), **delta,
            })
        if params.get("fmt") == ["chrome"]:
            events = await asyncio.to_thread(prof.chrome_events)
            return _http_response(200, {
                "traceEvents": events, "displayTimeUnit": "ms",
            })
        folded = await asyncio.to_thread(prof.folded)
        return _text_response(200, folded + ("\n" if folded else ""))

    async def _timeseries_get(self, query: str) -> bytes:
        """GET /timeseries: the metrics history's trend surface. Without
        params: the series index plus the flat ``timeseries_*`` status
        (the vocabulary /metrics exports as ``infinistore_timeseries_*``,
        ITS-C008). ``?metric=<series>[&window=<seconds>]``: the retained
        ``[t_s, value]`` points (monotonic-clock seconds); REPEATED
        ``metric`` params return every known series' points in one
        response under ``metrics`` (the ``tools.top`` sparkline fetch —
        one request per frame, not one per series; repeated params
        rather than a comma list because label values may contain
        commas). 404 for an unknown single series, 400 for a bad
        (non-finite) window."""
        if self.history is None:
            return _http_response(200, {
                "enabled": False, "error": "no metrics history attached",
            })
        params = urllib.parse.parse_qs(query)
        metrics = params.get("metric", [])
        if not metrics:
            return _http_response(200, {
                "enabled": True,
                "series": self.history.series_names(),
                **self.history.status(),
            })
        try:
            window = params.get("window", [None])[0]
            window_s = float(window) if window is not None else None
        except ValueError:
            return _http_response(400, {"error": "bad window"})
        if window_s is not None and not math.isfinite(window_s):
            # float('nan')/'inf' parse fine but nan poisons the horizon
            # compare and serializes as bare NaN — invalid JSON.
            return _http_response(400, {"error": "bad window"})
        if len(metrics) > 1:
            known = set(self.history.series_names())
            return _http_response(200, {
                "window_s": window_s,
                "metrics": {
                    m: self.history.points(m, window_s=window_s)
                    for m in metrics if m in known
                },
            })
        metric = metrics[0]
        if metric not in self.history.series_names():
            return _http_response(404, {
                "error": f"unknown series {metric!r}",
            })
        return _http_response(200, {
            "metric": metric,
            "window_s": window_s,
            "points": self.history.points(metric, window_s=window_s),
        })

    def _membership_get(self) -> bytes:
        """GET /membership: the epoch-stamped view (per-member states) plus
        the flat membership_*/reshard_* counter snapshot, verbatim from
        ``membership_status()`` — the counters checker (ITS-C005) holds
        this route to the status vocabulary."""
        if self.cluster is None:
            return _http_response(
                200, {"enabled": False, "error": "no cluster attached"}
            )
        self._sweep_owned_conns()
        view = self.cluster.membership.view()
        return _http_response(200, {
            "enabled": True,
            **view.as_dict(),
            **self.cluster.membership_status(),
        })

    def _structured_error(self, status: int, reason: str,
                          detail: str = "") -> bytes:
        """Structured JSON error body for the membership/gossip/bootstrap
        control surface: machine-readable ``reason`` plus the CURRENT
        epoch, so a stale gossiping peer (or a retrying operator script)
        can self-correct from the response instead of parsing prose
        (docs/membership.md)."""
        epoch = (
            self.cluster.membership.view().epoch
            if self.cluster is not None else 0
        )
        return _http_response(status, {
            "error": detail or reason, "reason": reason, "epoch": epoch,
        })

    async def _membership_post(self, body: bytes) -> bytes:
        """POST /membership: apply one membership transition.

        Body (JSON): ``{"action": "add", "host": ..., "service_port": ...,
        "member_id"?: ...}`` connects a new member and admits it JOINING
        (connect runs in a worker thread — the control plane must not block
        on a TCP connect, ITS-L001); ``{"action": "remove"|"mark_dead",
        "member_id": ...}`` drains / writes off an existing member. Returns
        the new epoch + status; errors are 400s with a structured body
        (``reason`` + current ``epoch``)."""
        if self.cluster is None:
            return self._structured_error(400, "no_cluster",
                                          "no cluster attached")
        try:
            req = json.loads(body.decode() or "{}")
        except ValueError as e:
            return self._structured_error(400, "bad_json", repr(e))
        action = req.get("action")
        try:
            if action == "add":
                view = await asyncio.to_thread(
                    self._add_member_blocking, req
                )
            elif action in ("remove", "mark_dead"):
                if "member_id" not in req:
                    return self._structured_error(
                        400, "missing_field", "member_id required"
                    )
                fn = (
                    self.cluster.remove_member if action == "remove"
                    else self.cluster.mark_dead
                )
                view = fn(req["member_id"])
            else:
                return self._structured_error(
                    400, "unknown_action", f"unknown action {action!r}"
                )
        except KeyError as e:
            # "add" without host/service_port, or a transition against a
            # member id the view does not know.
            reason = "missing_field" if action == "add" else "invalid_transition"
            return self._structured_error(400, reason, repr(e))
        except ValueError as e:
            # Rejected transitions (duplicate live id, bad state, last
            # placement member): the epoch in the body tells the caller
            # what view the rejection was judged against.
            return self._structured_error(400, "invalid_transition", repr(e))
        except TypeError as e:
            return self._structured_error(400, "bad_payload", repr(e))
        self._sweep_owned_conns()
        return _http_response(200, {
            "status": "ok",
            "epoch": view.epoch,
            **self.cluster.membership_status(),
        })

    async def _gossip_post(self, body: bytes) -> bytes:
        """POST /gossip: one half of an anti-entropy exchange
        (docs/membership.md, gossip section). The sender's epoch-stamped
        view merges into ours through the tombstone-aware lattice (off
        the event loop — a merge may dial a newly learned member); the
        response carries OUR post-merge view, which the sender merges
        back — so a single exchange converges both processes in either
        direction, and a stale sender self-corrects from the body.
        Errors are structured (``reason`` + current ``epoch``)."""
        if self.cluster is None:
            return self._structured_error(400, "no_cluster",
                                          "no cluster attached")
        try:
            req = json.loads(body.decode() or "{}")
        except ValueError as e:
            return self._structured_error(400, "bad_json", repr(e))
        try:
            merged = await asyncio.to_thread(
                self.cluster.merge_remote_view, req
            )
        except (KeyError, ValueError, TypeError) as e:
            return self._structured_error(400, "bad_payload", repr(e))
        self._sweep_owned_conns()
        return _http_response(200, {
            "status": "ok",
            "merged": bool(merged),
            **self.cluster.gossip_payload(),
        })

    async def _bootstrap_get(self, query: str) -> bytes:
        """GET /bootstrap: the cold-client snapshot — the epoch-stamped
        view plus a bounded catalog dump (root records with holder
        block-levels), enough for a fresh process with only a seed list
        to reconstruct placement from any live member
        (``ClusterKVConnector.bootstrap``). ``?limit=N`` bounds the
        catalog rows (default 4096; ``catalog_total`` reports the full
        size). Runs off-loop — the catalog walk is O(n_roots)."""
        if self.cluster is None:
            return self._structured_error(400, "no_cluster",
                                          "no cluster attached")
        params = urllib.parse.parse_qs(query)
        try:
            limit = int(params.get("limit", ["4096"])[0])
        except ValueError:
            return self._structured_error(400, "bad_limit", "bad limit")
        payload = await asyncio.to_thread(
            self.cluster.bootstrap_payload, limit
        )
        return _http_response(200, {"enabled": True, **payload})

    def _add_member_blocking(self, req: dict):
        """Connect + admit a new member (worker-thread half of POST add)."""
        from .config import ClientConfig
        from .lib import InfinityConnection

        host, port = req["host"], int(req["service_port"])
        member_id = req.get("member_id") or f"{host}:{port}"
        conn = InfinityConnection(ClientConfig(
            host_addr=host, service_port=port, log_level="error",
        ))
        try:
            conn.connect()
            view = self.cluster.add_member(conn, member_id=member_id)
        except BaseException:
            # Whatever failed — unreachable host, rejected transition — the
            # native connection must not leak across operator retries.
            try:
                conn.close()
            except Exception:
                pass
            raise
        # Admitted: the manage plane owns this connection until the member
        # goes terminal (_sweep_owned_conns).
        with self._conns_lock:
            self._owned_conns[member_id] = conn
        return view

    def _selftest(self) -> dict:
        """Loopback write/read/delete through the real data plane."""
        import numpy as np

        from .lib import ClientConfig, InfinityConnection

        key = "__selftest__"
        conn = InfinityConnection(
            ClientConfig(
                host_addr="127.0.0.1",
                service_port=self.config.service_port,
                log_level="error",
            )
        )
        try:
            conn.connect()
            data = np.arange(4096, dtype=np.uint8)
            conn.tcp_write_cache(key, data.ctypes.data, data.nbytes)
            back = conn.tcp_read_cache(key)
            ok = bool(np.array_equal(back, data))
            conn.delete_keys([key])
            return {"status": "ok" if ok else "corrupt", "roundtrip_bytes": int(data.nbytes)}
        finally:
            conn.close()

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, host=self.config.host, port=self.config.manage_port
        )
        Logger.info(f"manage plane on {self.config.host}:{self.config.manage_port}")

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


async def periodic_evict(config: ServerConfig):
    """Background eviction loop (reference server.py:157-186)."""
    while True:
        await asyncio.sleep(config.evict_interval)
        try:
            evicted = await asyncio.to_thread(
                _lib.evict_cache, config.evict_min_threshold, config.evict_max_threshold
            )
            if evicted:
                Logger.info(f"periodic evict: {evicted} entries")
        except Exception as e:
            Logger.error(f"periodic evict failed: {e}")


async def serve(config: ServerConfig) -> None:
    register_server(None, config)
    # /proc write = file IO; keep it off the event loop (ITS-L002).
    await asyncio.to_thread(prevent_oom)
    # Standing metrics history (docs/observability.md, time-series
    # section): the CLI server trends its own /metrics families so
    # GET /timeseries and the tools.top sparklines work out of the box —
    # one bounded source pass per interval (~0.5ms each; the bench's
    # timeseries_pass_cost receipt). INFINISTORE_TPU_HISTORY=0 opts out.
    history = None
    if os.environ.get("INFINISTORE_TPU_HISTORY", "1") not in ("", "0"):
        history = telemetry.MetricsHistory()
        # The manage plane binds config.host: loopback only reaches it on
        # a wildcard bind — a specific-interface bind must be scraped at
        # that address or the self-source fails every pass forever.
        self_host = (
            "127.0.0.1" if config.host in ("", "0.0.0.0", "::")
            else config.host
        )
        history.add_source("", telemetry.metrics_http_source(
            self_host, config.manage_port
        ))
    manage = ManageServer(config, history=history)
    await manage.start()
    if history is not None:
        history.start()
    tasks = []
    if config.evict_enabled:
        tasks.append(asyncio.create_task(periodic_evict(config)))

    stop_event = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop_event.set)
    Logger.info(f"infinistore-tpu serving on {config.host}:{config.service_port}")
    try:
        await stop_event.wait()
    finally:
        for t in tasks:
            t.cancel()
        if history is not None:
            await asyncio.to_thread(history.stop)
        await manage.stop()
        unregister_server()


def main(argv=None) -> int:
    config = parse_args(argv)
    config.verify()
    Logger.set_log_level(config.log_level)
    try:
        asyncio.run(serve(config))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
