"""Ulysses-style all-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

The other canonical long-context sharding, complementing ring attention:
instead of rotating K/V around a ring, ONE all-to-all per tensor re-shards
q/k/v from sequence-sharded [B, S/P, H, D] to head-sharded [B, S, H/P, D],
attention runs LOCALLY over the full sequence per head group (no per-step
collectives, exact softmax — no online accumulation needed), and one
all-to-all brings the output back to sequence sharding. Total comms: 4
all-to-alls per attention vs ring's P-1 permutes of K/V — Ulysses wins when
heads divide the mesh and the interconnect favors fewer, larger collectives;
ring wins when H < P or memory for the full-sequence scores is tight.

Requires n_heads % axis_size == 0 and S % axis_size == 0. Exact against
dense attention (tested, causal and full, gradients included).
"""

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax: the experimental home (see paged_attention)
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import _axis_size


def _ulysses_local(q, k, v, axis: str, causal: bool):
    """Runs INSIDE shard_map: q/k/v [B, S_loc, H, D] (sequence-sharded)."""
    ring = _axis_size(axis)
    b, s_loc, h, d = q.shape
    assert h % ring == 0, f"n_heads={h} must divide the {axis} axis ({ring})"

    def seq_to_heads(x):
        # [B, S_loc, H, D] -> [B, S, H_loc, D]: split the head dim P ways,
        # tile the pieces along sequence — after the exchange this shard
        # holds the FULL sequence for its head group.
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # [B, S, H_loc, D] -> [B, S_loc, H, D]: the inverse exchange.
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qf, kf, vf = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = ring * s_loc
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # Framework-wide attention contract (models/llama.py _attention): f32
    # softmax statistics, HIGHEST-precision dots (XLA's DEFAULT runs f32
    # operands in reduced-precision passes on TPU).
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk",
        qf.astype(jnp.float32),
        kf.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ) * scale
    if causal:
        cm = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(cm[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        p,
        vf.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ).astype(q.dtype)
    return heads_to_seq(out)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "causal"))
def ulysses_attention(
    q: jax.Array,  # [B, S, H, D], S sharded over `axis`
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """All-to-all sequence-parallel attention; in/out sharded [B, S@sp, H, D].

    K/V head counts must equal Q's (repeat GQA heads first). See the module
    docstring for when to prefer this over ring attention.
    """
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return fn(*(jax.device_put(x, sharding) for x in (q, k, v)))
