"""Small Llama-style transformer with a paged KV cache, in plain JAX.

TPU-idiomatic by construction: einsum everywhere (MXU), bfloat16 activations,
static shapes, GQA attention, RoPE, RMSNorm, SwiGLU. The KV cache uses the
paged layout of infinistore_tpu.tpu.paged ([num_blocks, block_tokens,
n_kv_heads, head_dim] per layer), so prefill output can be streamed to the
store with LayerwiseKVWriter and decode can resume from fetched blocks — the
role vLLM plays for the reference store.

Sharding conventions (used by __graft_entry__.dryrun_multichip and the
train_step): logical axes are ("dp", "tp"[, "ep"]) — batch over dp, attention
heads / ffn hidden over tp, experts over ep (n_experts > 0 switches the FFN
to a soft mixture-of-experts whose expert-major weight tensors shard over the
ep axis; XLA computes local experts and inserts the combine collective), with
sequence-sharded activations where XLA chooses.
"""

import functools
from dataclasses import dataclass
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..tpu.flash_prefill import flash_prefill_attention
from ..tpu.paged import PagedKVCacheSpec, scatter_blocks
from ..tpu.paged_attention import (
    paged_decode_attention_batched,
    paged_decode_attention_rows,
)

Params = Dict[str, jax.Array]
Caches = List[Tuple[jax.Array, jax.Array]]


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 512
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_dim: int = 256
    # > 0 switches every FFN to a soft mixture of experts: expert-major
    # weights [n_experts, ...] shard over an "ep" mesh axis (expert
    # parallelism); a router picks per-token gates and the combine reduces
    # across experts (psum over ep under jit).
    n_experts: int = 0
    block_tokens: int = 8
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def kv_spec(self, num_blocks: int) -> PagedKVCacheSpec:
        """Paged-KV cache spec matching this model's layers/heads/dtype."""
        return PagedKVCacheSpec(
            num_layers=self.n_layers,
            num_blocks=num_blocks,
            block_tokens=self.block_tokens,
            num_kv_heads=self.n_kv_heads,
            head_dim=self.head_dim,
            dtype=self.dtype,
        )


def init_params(config: LlamaConfig, key: jax.Array) -> Params:
    """He-scaled dense params as a flat dict (layer-prefixed keys)."""
    keys = iter(jax.random.split(key, 4 + 8 * config.n_layers))

    def dense(k, shape):
        scale = 1.0 / np.sqrt(shape[0])
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(
            config.dtype
        )

    p: Params = {
        "embed": dense(next(keys), (config.vocab, config.dim)),
        "final_norm": jnp.ones((config.dim,), dtype=config.dtype),
        "lm_head": dense(next(keys), (config.dim, config.vocab)),
    }
    hd = config.head_dim
    for layer in range(config.n_layers):
        pre = f"l{layer}."
        p[pre + "attn_norm"] = jnp.ones((config.dim,), dtype=config.dtype)
        p[pre + "wq"] = dense(next(keys), (config.dim, config.n_heads, hd))
        p[pre + "wk"] = dense(next(keys), (config.dim, config.n_kv_heads, hd))
        p[pre + "wv"] = dense(next(keys), (config.dim, config.n_kv_heads, hd))
        p[pre + "wo"] = dense(next(keys), (config.n_heads, hd, config.dim))
        p[pre + "ffn_norm"] = jnp.ones((config.dim,), dtype=config.dtype)
        if config.n_experts > 0:
            p[pre + "router"] = dense(next(keys), (config.dim, config.n_experts))
            p[pre + "w_gate_up_moe"] = dense(
                next(keys), (config.n_experts, config.dim, 2, config.ffn_dim)
            )
            p[pre + "w_down_moe"] = dense(
                next(keys), (config.n_experts, config.ffn_dim, config.dim)
            )
        else:
            p[pre + "w_gate_up"] = dense(next(keys), (config.dim, 2, config.ffn_dim))
            p[pre + "w_down"] = dense(next(keys), (config.ffn_dim, config.dim))
    return p


def _rms_norm(x: jax.Array, w: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * w


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [..., seq, heads, head_dim], positions: [..., seq]."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = positions[..., :, None].astype(jnp.float32) * freqs  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., :, None, :]
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, T, KVH, D]
    v: jax.Array,  # [B, T, KVH, D]
    mask: jax.Array,  # [B, S, T] True = attend
) -> jax.Array:
    """Dense attention with the framework-wide numeric contract: logits and
    softmax statistics in float32 (preferred_element_type keeps the MXU's
    native f32 accumulation for bf16 operands; HIGHEST stops XLA from
    running f32 operands in reduced-precision passes), output cast back to
    the query dtype. The fused paged decode kernel
    (tpu/paged_attention.py) and the ring/Ulysses paths follow the same
    contract, so every attention implementation agrees to float32 rounding
    on every backend."""
    groups = q.shape[2] // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = (
        jnp.einsum(
            "bshd,bthd->bhst",
            q,
            k,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        * scale
    )
    logits = jnp.where(mask[:, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhst,bthd->bshd",
        probs,
        v.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return out.astype(q.dtype)


def _block(params: Params, layer: int, x, k, v, q_positions, mask, config):
    """Shared transformer block math given already-materialized K/V context.

    x: [B, S, dim]; k/v: [B, T, KVH, D] (full attention context). ``mask``
    is [B, S, T] (True = attend), or None for plain causal — the None form
    routes through the flash prefill kernel on TPU (no S x T logits
    materialized; forward-only, so training losses pass an explicit mask
    and keep the differentiable dense path)."""
    pre = f"l{layer}."
    q = _q_proj(params, layer, x, q_positions, config)
    if mask is None:
        attn = flash_prefill_attention(q, k, v, causal=True)
    else:
        attn = _attention(q, k, v, mask)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, params[pre + "wo"])
    return _ffn(params, layer, x, config)


def _ffn(params: Params, layer: int, x, config):
    """FFN half of the block (dense or soft-MoE), shared by the dense path
    and the fused-decode path."""
    pre = f"l{layer}."
    h = _rms_norm(x, params[pre + "ffn_norm"])
    if config.n_experts > 0:
        # Soft MoE, expert-major: every einsum keeps the expert axis e
        # outermost so weights sharded P("ep", ...) compute their local
        # experts and XLA reduces the combine across the ep axis. Dense
        # (all tokens x all experts) by design — compiler-friendly static
        # shapes; top-k routing sparsity is a serving optimization, not
        # needed to exercise the parallelism.
        gates = jax.nn.softmax(
            jnp.einsum("bsd,de->bse", h, params[pre + "router"]).astype(jnp.float32),
            axis=-1,
        ).astype(h.dtype)
        gate_up = jnp.einsum("bsd,edcf->bsecf", h, params[pre + "w_gate_up_moe"])
        ffn = jax.nn.silu(gate_up[:, :, :, 0]) * gate_up[:, :, :, 1]  # [B,S,E,F]
        out = jnp.einsum("bse,bsef,efd->bsd", gates, ffn, params[pre + "w_down_moe"])
        return x + out
    gate_up = jnp.einsum("bsd,dcf->bscf", h, params[pre + "w_gate_up"])
    ffn = jax.nn.silu(gate_up[:, :, 0]) * gate_up[:, :, 1]
    return x + jnp.einsum("bsf,fd->bsd", ffn, params[pre + "w_down"])


def _q_proj(params: Params, layer: int, x, positions, config):
    pre = f"l{layer}."
    h = _rms_norm(x, params[pre + "attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wq"])
    return _rope(q, positions, config.rope_theta)


def _kv_proj(params: Params, layer: int, x, positions, config):
    pre = f"l{layer}."
    h = _rms_norm(x, params[pre + "attn_norm"])
    k = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wv"])
    k = _rope(k, positions, config.rope_theta)
    return k, v


# ---------------------------------------------------------------------------
# Paged-cache inference. Batch = 1 sequence per call (engine loops/vmaps);
# the cache is shared across sequences via the block table, exactly the
# paged-attention model the store serves.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("config",))
def prefill(
    params: Params,
    tokens: jax.Array,  # [S] int32, S % block_tokens == 0
    caches: Caches,  # per layer (K, V) paged arrays
    block_table: jax.Array,  # [S // block_tokens] int32 cache block ids
    config: LlamaConfig,
) -> Tuple[jax.Array, Caches]:
    """Full prompt pass; writes K/V into the paged cache blocks listed in
    block_table. Returns (last-token logits, updated caches)."""
    s = tokens.shape[0]
    bt = config.block_tokens
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    x = jnp.take(params["embed"], tokens, axis=0)[None]  # [1, S, dim]
    mask = None  # plain causal -> flash prefill kernel on TPU (_block)

    new_caches: Caches = []
    for layer, (k_cache, v_cache) in enumerate(caches):
        k, v = _kv_proj(params, layer, x, positions, config)
        x = _block(params, layer, x, k, v, positions, mask, config)
        # Scatter this prompt's K/V into its cache blocks.
        k_blocks = k[0].reshape(s // bt, bt, config.n_kv_heads, config.head_dim)
        v_blocks = v[0].reshape(s // bt, bt, config.n_kv_heads, config.head_dim)
        new_caches.append(
            (
                scatter_blocks(k_cache, block_table, k_blocks),
                scatter_blocks(v_cache, block_table, v_blocks),
            )
        )
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"])
    return logits[0, -1], new_caches


@functools.partial(jax.jit, static_argnames=("config", "max_blocks"))
def decode_step(
    params: Params,
    token: jax.Array,  # [] int32
    position: jax.Array,  # [] int32 absolute position of `token`
    caches: Caches,
    block_table: jax.Array,  # [max_blocks] int32 (padded with any valid id)
    config: LlamaConfig,
    max_blocks: int,
) -> Tuple[jax.Array, Caches]:
    """One decode token against the paged cache: append this token's K/V into
    its block slot, then fused paged attention over the context blocks
    (tpu/paged_attention.py: on TPU each context block crosses HBM exactly
    once — no materialized gather; gather+dense XLA elsewhere, same f32
    softmax contract). ``max_blocks`` must equal the padded block_table
    length (validated at trace time — a mismatch fails loudly, as the old
    gather-and-reshape path did). Returns (logits, caches).

    This is the B=1 wrapper over ``decode_step_batched`` — one decode body
    to maintain, mirroring the same pattern in tpu/paged_attention.py."""
    if block_table.shape[0] != max_blocks:
        raise ValueError(
            f"block_table has {block_table.shape[0]} entries, expected "
            f"max_blocks={max_blocks} (pad the table to the static bound)"
        )
    logits, new_caches = decode_step_batched(
        params,
        token[None],
        position[None],
        caches,
        block_table[None],
        config,
        max_blocks,
    )
    return logits[0], new_caches


@functools.partial(jax.jit, static_argnames=("config", "max_blocks"))
def verify_step_batched(
    params: Params,
    tokens: jax.Array,  # [B, K] int32, one token chunk per live request
    positions: jax.Array,  # [B, K] int32 absolute position of each token
    caches: Caches,  # SHARED paged cache across the wave
    block_tables: jax.Array,  # [B, max_blocks] int32 (rows padded)
    config: LlamaConfig,
    max_blocks: int,
) -> Tuple[jax.Array, Caches]:
    """THE paged-inference body: a wave of B requests each advancing a
    K-token chunk against the shared cache in one launch per layer.

    Every per-request inference entry point is a view of this: K=1 is
    batched decode (``decode_step_batched``), B=1 with K>1 is chunked
    continuation prefill / speculative verification (``prefill_continue``,
    ``speculative_verify``), and B>1 with K>1 is a MIXED wave — some
    requests decoding one token, others verifying drafts — which is what
    lets a continuous-batching engine fold speculative decoding into its
    lockstep waves (engine.py WaveDecoder) instead of running spec
    requests out-of-band.

    Each row inserts its K/V at (table[pos // bt], pos % bt), then one
    batched fused attention launch covers all B*K rows, each masked to its
    own position + 1 (tpu/paged_attention.py). Requests own disjoint
    blocks (the engine's block-table manager guarantees it); duplicate
    rows WITHIN a request (wave/chunk padding that repeats a row) write
    identical bytes and are therefore value-safe. Rows may attend sibling
    rows' K/V within the chunk: inserts complete before attention, and
    per-row masking keeps causality. Returns ([B, K, vocab] logits,
    updated caches)."""
    bsz, kk = tokens.shape
    if block_tables.shape != (bsz, max_blocks):
        raise ValueError(
            f"block_tables must be [{bsz}, {max_blocks}] (one padded row per "
            f"request), got {block_tables.shape}"
        )
    if positions.shape != (bsz, kk):
        raise ValueError(
            f"positions must match tokens' [{bsz}, {kk}], got {positions.shape}"
        )
    bt = config.block_tokens
    x = jnp.take(params["embed"], tokens, axis=0)  # [B, K, dim]

    flat_pos = positions.reshape(-1)  # [B*K]
    block_idx = jnp.take_along_axis(
        block_tables, positions // bt, axis=1
    ).reshape(-1)  # [B*K]
    slots = flat_pos % bt
    row_tables = jnp.repeat(block_tables, kk, axis=0)  # [B*K, max_blocks]

    new_caches: Caches = []
    for layer, (k_cache, v_cache) in enumerate(caches):
        k, v = _kv_proj(params, layer, x, positions, config)  # [B, K, KVH, D]
        k_cache = k_cache.at[block_idx, slots].set(
            k.reshape(bsz * kk, *k.shape[2:]).astype(k_cache.dtype)
        )
        v_cache = v_cache.at[block_idx, slots].set(
            v.reshape(bsz * kk, *v.shape[2:]).astype(v_cache.dtype)
        )
        pre = f"l{layer}."
        q = _q_proj(params, layer, x, positions, config)  # [B, K, H, D]
        attn = paged_decode_attention_batched(
            q.reshape(bsz * kk, *q.shape[2:]), k_cache, v_cache,
            row_tables, flat_pos + 1,
        ).reshape(bsz, kk, *q.shape[2:])  # [B, K, H, D]
        x = x + jnp.einsum("bshk,hkd->bsd", attn, params[pre + "wo"])
        x = _ffn(params, layer, x, config)
        new_caches.append((k_cache, v_cache))
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"])
    return logits, new_caches


@functools.partial(jax.jit, static_argnames=("config", "max_blocks"))
def verify_step_ragged(
    params: Params,
    tokens: jax.Array,  # [T] int32, the wave's chunks CONCATENATED row-major
    positions: jax.Array,  # [T] int32 absolute position of each flat token
    row_of: jax.Array,  # [T] int32 owning request per flat token (sorted)
    pages: jax.Array,  # [P] int32 flat attention page list (RaggedWaveMeta)
    page_rows: jax.Array,  # [P + 1] int32 owning flat token per page
    page_starts: jax.Array,  # [T] int32 first page per flat token
    caches: Caches,  # SHARED paged cache across the wave
    block_tables: jax.Array,  # [B, max_blocks] int32 (rows padded)
    config: LlamaConfig,
    max_blocks: int,
) -> Tuple[jax.Array, Caches]:
    """The RAGGED form of ``verify_step_batched``: a mixed wave where
    request chunks keep their OWN lengths — the wave is one flat [T] token
    list (T = sum of chunk lengths) with per-token request/page metadata,
    instead of a [B, K] rectangle padded to the widest chunk.

    Why it exists: the rectangular wave pays B x max(K_i) rows per launch
    (a lone 8-token verification chunk makes every decoding request pad
    7 duplicate rows), and its attention grid scans max_blocks table
    entries per row. Here the launch covers exactly the real rows (plus
    tail-bucket padding that repeats the LAST flat row — same-bytes
    scatter, value-safe like the rectangular padding, but one row instead
    of a rectangle), and on TPU the attention walks the flat page list
    (tpu/paged_attention.py ragged kernel): sum(ceil((pos_t + 1) / bt))
    block folds, no padding to the wave max.

    Per-token semantics are IDENTICAL to ``verify_step_batched`` — each
    flat token inserts its K/V at (table[pos // bt], pos % bt) and attends
    its own prefix masked to pos + 1 — so a mixed ragged wave equals
    per-request sequential decode byte-for-byte on the cache and the
    logits (pinned by the engine tests). ``block_tables`` rows beyond the
    real requests (bucket padding) are never referenced by any flat token:
    a padded WAVE ROW no longer scatters or attends at all, it is simply
    absent. Returns ([T, vocab] logits, updated caches)."""
    t = tokens.shape[0]
    if positions.shape != (t,) or row_of.shape != (t,):
        raise ValueError(
            f"positions/row_of must match tokens' [{t}], got "
            f"{positions.shape}/{row_of.shape}"
        )
    if page_starts.shape != (t,):
        raise ValueError(f"page_starts must be [{t}], got {page_starts.shape}")
    if block_tables.ndim != 2 or block_tables.shape[1] != max_blocks:
        raise ValueError(
            f"block_tables must be [B, {max_blocks}], got {block_tables.shape}"
        )
    bt = config.block_tokens
    x = jnp.take(params["embed"], tokens, axis=0)[None]  # [1, T, dim]
    pos2d = positions[None]  # [1, T]

    row_tables = jnp.take(block_tables, row_of, axis=0)  # [T, max_blocks]
    block_idx = jnp.take_along_axis(
        row_tables, (positions // bt)[:, None], axis=1
    )[:, 0]
    slots = positions % bt
    seq_lens = positions + 1

    new_caches: Caches = []
    for layer, (k_cache, v_cache) in enumerate(caches):
        k, v = _kv_proj(params, layer, x, pos2d, config)  # [1, T, KVH, D]
        k_cache = k_cache.at[block_idx, slots].set(k[0].astype(k_cache.dtype))
        v_cache = v_cache.at[block_idx, slots].set(v[0].astype(v_cache.dtype))
        pre = f"l{layer}."
        q = _q_proj(params, layer, x, pos2d, config)  # [1, T, H, D]
        attn = paged_decode_attention_rows(
            q[0], k_cache, v_cache, row_tables, seq_lens,
            pages, page_rows, page_starts,
        )[None]  # [1, T, H, D]
        x = x + jnp.einsum("bshk,hkd->bsd", attn, params[pre + "wo"])
        x = _ffn(params, layer, x, config)
        new_caches.append((k_cache, v_cache))
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"])
    return logits[0], new_caches


def prefill_continue(
    params: Params,
    tokens: jax.Array,  # [S_c] int32, the suffix chunk
    start_pos: jax.Array,  # [] int32, absolute position of tokens[0]
    caches: Caches,
    block_table: jax.Array,  # [max_blocks] int32 (padded)
    config: LlamaConfig,
    max_blocks: int,
) -> Tuple[jax.Array, Caches]:
    """Chunked continuation prefill: compute a multi-token suffix against an
    already-populated paged prefix in ONE call per layer (the engine's
    chunked-prefill resume path — vLLM's treatment of a prefix-cache hit).
    Token-by-token ``decode_step`` costs S_c launches per layer and GEMV
    matmuls; this inserts the whole chunk's K/V and attends all chunk rows
    in one batched kernel launch (each row masked to its own prefix length),
    with chunk-wide GEMMs for the projections and FFN. Semantically equal to
    the decode loop (tested). Returns ([S_c, vocab] logits, caches).

    This is the B=1 view of ``verify_step_batched`` — one inference body
    to maintain."""
    if block_table.shape[0] != max_blocks:
        raise ValueError(
            f"block_table has {block_table.shape[0]} entries, expected "
            f"max_blocks={max_blocks} (pad the table to the static bound)"
        )
    s_c = tokens.shape[0]
    positions = start_pos + jnp.arange(s_c, dtype=jnp.int32)
    logits, new_caches = verify_step_batched(
        params,
        tokens[None],
        positions[None],
        caches,
        block_table[None],
        config,
        max_blocks,
    )
    return logits[0], new_caches


def speculative_verify(
    params: Params,
    draft,  # [D] int sequence/array of draft tokens (draft[0] already
    #         validated by the caller against its previous step's logits)
    start_pos,  # int, absolute position of draft[0]
    caches: Caches,
    block_table: jax.Array,  # [max_blocks] int32 (padded)
    config: LlamaConfig,
    max_blocks: int,
    pad_to: int = 0,
):
    """Score a whole speculative draft in ONE chunked pass and accept its
    longest greedy-consistent prefix.

    ``prefill_continue`` processes all D draft tokens at once (each row
    attends its own prefix); row i's argmax is the target model's next
    token after ``draft[:i+1]``, so ``draft[i+1]`` is accepted iff it
    equals that argmax. Returns ``(n_accepted, next_token, caches)`` where
    ``next_token`` is the target model's continuation after the accepted
    prefix — the token the engine emits alongside the accepted draft.

    Rollback is free by construction: rejected draft positions DID insert
    K/V into their slots, but every later decode masks attention by
    ``position + 1`` (tpu/paged_attention.py), so stale slots beyond the
    accepted point are never attended and are overwritten when real tokens
    reach those positions. The caller only rewinds its position counter.
    Cites the reference's cache-semantics stance (SURVEY.md §5.3): wrong
    speculation costs recompute, never correctness.

    ``pad_to``: prefill_continue is jitted, so every DISTINCT draft length
    recompiles. Engines with variable-length drafts pass a fixed
    ``pad_to`` >= D: the draft is padded (with its last token — the pad
    rows' K/V land beyond the accepted point and are masked/overwritten
    like any rejection) and acceptance is computed over the true D only,
    so one compiled shape serves every round."""
    draft_host = np.asarray(draft, dtype=np.int32)
    d = int(draft_host.shape[0])
    if d == 0:
        raise ValueError("speculative_verify needs a non-empty draft")
    span = pad_to or d
    if int(start_pos) + span > max_blocks * config.block_tokens:
        # jnp.take would CLIP out-of-table block indices and silently
        # overwrite the last block's slots — fail loudly instead.
        raise ValueError(
            f"draft span [{int(start_pos)}, {int(start_pos) + span}) exceeds "
            f"the table's {max_blocks * config.block_tokens}-token capacity"
        )
    if pad_to:
        if pad_to < d:
            raise ValueError(f"pad_to={pad_to} < draft length {d}")
        draft_host = np.concatenate(
            [draft_host, np.full(pad_to - d, draft_host[-1], np.int32)]
        )
    logits, caches = prefill_continue(
        params, jnp.asarray(draft_host), jnp.int32(start_pos), caches,
        block_table, config, max_blocks,
    )
    # ONE device->host transfer per round (the [D]-sized argmaxes; the
    # draft comparison side stays host-resident) — this runs every
    # speculation round on the decode hot path.
    preds = np.asarray(jnp.argmax(logits, axis=-1))  # preds[i] follows draft[:i+1]
    ok = preds[: d - 1] == draft_host[1:d]  # draft[i+1] consistent?
    n_accepted = 1 + int(np.argmin(ok)) if not ok.all() else d
    next_token = int(preds[n_accepted - 1])
    return n_accepted, next_token, caches


def decode_step_batched(
    params: Params,
    tokens: jax.Array,  # [B] int32, one next-token per live request
    positions: jax.Array,  # [B] int32 absolute position of each token
    caches: Caches,  # SHARED paged cache across the wave
    block_tables: jax.Array,  # [B, max_blocks] int32 (rows padded)
    config: LlamaConfig,
    max_blocks: int,
) -> Tuple[jax.Array, Caches]:
    """One decode step for a WAVE of requests sharing the paged cache — the
    continuous-batching engine's inner loop (every live request advances one
    token per step). Per-token semantics are identical to ``decode_step``
    (tested); the win is paying the model's dispatch and kernel-launch cost
    once per wave instead of once per request. Returns ([B, vocab] logits,
    updated caches).

    This is the K=1 view of ``verify_step_batched`` — one inference body
    to maintain."""
    logits, new_caches = verify_step_batched(
        params,
        tokens[:, None],
        positions[:, None],
        caches,
        block_tables,
        config,
        max_blocks,
    )
    return logits[:, 0], new_caches


# ---------------------------------------------------------------------------
# Layerwise inference entry points (disaggregated prefill -> decode handoff,
# docs/disaggregation.md). The monolithic ``prefill``/``verify_step_batched``
# bodies are re-expressed one layer per jitted call so a prefill engine can
# SHIP layer l's KV while layer l+1 computes, and a decode engine can gate
# each layer's attention on that layer's install alone (the watermark rule).
# Both handoff directions — streamed prefill and the fallback recompute —
# use THESE functions, and the watermarked and blocking decode paths share
# ``decode_wave_layer``, so "overlapped equals blocking byte-for-byte" holds
# by construction regardless of how XLA fuses across the per-layer
# boundaries.
# ---------------------------------------------------------------------------


@jax.jit
def embed_prompt(params: Params, tokens: jax.Array) -> jax.Array:
    """[S] prompt tokens -> [1, S, dim] activations (the layerwise prefill
    chain's entry)."""
    return jnp.take(params["embed"], tokens, axis=0)[None]


@functools.partial(jax.jit, static_argnames=("config", "layer"))
def prefill_layer(
    params: Params,
    x: jax.Array,  # [1, S, dim] activations entering this layer
    k_cache: jax.Array,  # this LAYER's paged K array
    v_cache: jax.Array,  # this LAYER's paged V array
    block_table: jax.Array,  # [S // block_tokens] int32 cache block ids
    config: LlamaConfig,
    layer: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One layer of the whole-prompt prefill: project this layer's K/V,
    scatter them into the layer's cache blocks, and run the block. Returns
    ``(x_next, k_cache, v_cache)`` — ``x_next`` feeds ``layer + 1`` while
    the caller ships the freshly scattered K/V (the streaming overlap).
    Chaining layers 0..L-1 then ``lm_logits`` is semantically equal to
    ``prefill`` (same per-layer math, pinned by tests)."""
    s = x.shape[1]
    bt = config.block_tokens
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    k, v = _kv_proj(params, layer, x, positions, config)
    x = _block(params, layer, x, k, v, positions, None, config)
    k_blocks = k[0].reshape(s // bt, bt, config.n_kv_heads, config.head_dim)
    v_blocks = v[0].reshape(s // bt, bt, config.n_kv_heads, config.head_dim)
    return (
        x,
        scatter_blocks(k_cache, block_table, k_blocks),
        scatter_blocks(v_cache, block_table, v_blocks),
    )


@jax.jit
def lm_logits(params: Params, x: jax.Array) -> jax.Array:
    """Final norm + LM head over [B, S, dim] activations (the layerwise
    chains' exit; [B, S, vocab] logits)."""
    x = _rms_norm(x, params["final_norm"])
    return jnp.einsum("bsd,dv->bsv", x, params["lm_head"])


@jax.jit
def embed_wave(params: Params, tokens: jax.Array) -> jax.Array:
    """[B, K] wave tokens -> [B, K, dim] activations (the layerwise decode
    chain's entry)."""
    return jnp.take(params["embed"], tokens, axis=0)


@functools.partial(jax.jit, static_argnames=("config", "layer", "max_blocks"))
def decode_wave_layer(
    params: Params,
    x: jax.Array,  # [B, K, dim] activations entering this layer
    positions: jax.Array,  # [B, K] int32 absolute positions
    k_cache: jax.Array,  # this LAYER's paged K array
    v_cache: jax.Array,  # this LAYER's paged V array
    block_tables: jax.Array,  # [B, max_blocks] int32 (rows padded)
    config: LlamaConfig,
    layer: int,
    max_blocks: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One layer of ``verify_step_batched``'s wave body: insert the wave's
    K/V at (table[pos // bt], pos % bt), fused paged attention over this
    layer's cache, residual + FFN. Returns ``(x_next, k_cache, v_cache)``.

    The watermark-gated decode admission (disagg.py) calls this only after
    THIS layer's prefix KV installed — layer l's attention never reads
    bytes still in flight — and the blocking fetch-all path chains the same
    function, so the two paths agree byte-for-byte on logits and caches."""
    bsz, kk = positions.shape
    if block_tables.shape != (bsz, max_blocks):
        raise ValueError(
            f"block_tables must be [{bsz}, {max_blocks}] (one padded row per "
            f"request), got {block_tables.shape}"
        )
    bt = config.block_tokens
    flat_pos = positions.reshape(-1)
    block_idx = jnp.take_along_axis(
        block_tables, positions // bt, axis=1
    ).reshape(-1)
    slots = flat_pos % bt
    row_tables = jnp.repeat(block_tables, kk, axis=0)
    k, v = _kv_proj(params, layer, x, positions, config)
    k_cache = k_cache.at[block_idx, slots].set(
        k.reshape(bsz * kk, *k.shape[2:]).astype(k_cache.dtype)
    )
    v_cache = v_cache.at[block_idx, slots].set(
        v.reshape(bsz * kk, *v.shape[2:]).astype(v_cache.dtype)
    )
    pre = f"l{layer}."
    q = _q_proj(params, layer, x, positions, config)
    attn = paged_decode_attention_batched(
        q.reshape(bsz * kk, *q.shape[2:]), k_cache, v_cache,
        row_tables, flat_pos + 1,
    ).reshape(bsz, kk, *q.shape[2:])
    x = x + jnp.einsum("bshk,hkd->bsd", attn, params[pre + "wo"])
    x = _ffn(params, layer, x, config)
    return x, k_cache, v_cache


# ---------------------------------------------------------------------------
# Training step (dense attention, no cache) — exercised by the multichip
# dryrun with dp/tp shardings.
# ---------------------------------------------------------------------------


def loss_fn(params: Params, tokens: jax.Array, config: LlamaConfig) -> jax.Array:
    """Next-token cross entropy over [B, S] token batches."""
    b, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)[None].repeat(b, axis=0)
    x = jnp.take(params["embed"], tokens, axis=0)
    mask = positions[:, :, None] >= positions[:, None, :]
    for layer in range(config.n_layers):
        k, v = _kv_proj(params, layer, x, positions, config)
        x = _block(params, layer, x, k, v, positions, mask, config)
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean()


@functools.partial(jax.jit, static_argnames=("config",), donate_argnums=(0,))
def train_step(
    params: Params, tokens: jax.Array, config: LlamaConfig, lr: float = 1e-3
) -> Tuple[Params, jax.Array]:
    """One SGD step on next-token loss; returns (new_params, loss). Shards
    follow the inputs (pjit-compatible: used by the multichip dryrun)."""
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, config)
    new_params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), params, grads)
    return new_params, loss
