"""GPipe-style pipeline parallelism over a "pp" mesh axis.

Completes the dryrun's parallelism alphabet (dp/tp/sp/ep/pp): layers split
into S stages, one stage per shard of the "pp" axis; a batch splits into M
microbatches that flow through the stages with `lax.ppermute` carrying
activations stage->stage inside a `lax.scan` over M + S - 1 ticks (the
classic GPipe fill/steady/drain schedule). Everything is one jitted SPMD
program — no host round-trips between ticks — and the math is EXACTLY the
dense forward's (tested: pp loss == loss_fn loss to float tolerance), so
gradients flow through the permutes (ppermute transposes to the reverse
permute) and a pipeline training step is just value_and_grad of this loss.

The reference has no parallelism at all (SURVEY.md §2); this exists so the
store's dryrun exercises every sharding its SPMD clients use.
"""

import functools
from typing import Dict

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax: the experimental home (see paged_attention)
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import _pcast_varying

from .llama import LlamaConfig, Params, _block, _kv_proj, _rms_norm

# Per-layer weight names (dense FFN config; MoE adds its own, pipeline keeps
# to the dense variant for clarity).
_LAYER_WEIGHTS = ("attn_norm", "wq", "wk", "wv", "wo", "ffn_norm", "w_gate_up", "w_down")
_SHARED = ("embed", "final_norm", "lm_head")


def stack_stage_params(params: Params, config: LlamaConfig, stages: int) -> Dict:
    """Restack flat per-layer params into stage-major tensors.

    Per-layer weights become [stages, layers_per_stage, ...] (leading axis
    sharded over "pp"); embed/final_norm/lm_head stay replicated. Requires
    n_layers % stages == 0 and a dense (non-MoE) config.
    """
    if config.n_experts > 0:
        raise ValueError("pipeline demo covers the dense FFN config")
    if config.n_layers % stages != 0:
        raise ValueError(f"n_layers={config.n_layers} not divisible by {stages} stages")
    lps = config.n_layers // stages
    out: Dict = {name: params[name] for name in _SHARED}
    for w in _LAYER_WEIGHTS:
        out[w] = jnp.stack(
            [
                jnp.stack([params[f"l{s * lps + i}.{w}"] for i in range(lps)])
                for s in range(stages)
            ]
        )
    return out


def _stage_forward(stage_params, x, positions, mask, config: LlamaConfig):
    """Apply this stage's layers_per_stage layers to x (same math as the
    dense loss_fn loop, via the shared _block/_kv_proj)."""
    lps = stage_params["wq"].shape[0]
    for i in range(lps):
        layer_view = {f"l0.{w}": stage_params[w][i] for w in _LAYER_WEIGHTS}
        k, v = _kv_proj(layer_view, 0, x, positions, config)
        x = _block(layer_view, 0, x, k, v, positions, mask, config)
    return x


def pp_loss_fn(
    stacked: Dict,
    tokens: jax.Array,  # [B, S] int32, replicated
    config: LlamaConfig,
    stages: int,
    microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Pipeline next-token loss — call INSIDE shard_map over `axis` (each
    shard's stacked per-layer weights carry a leading local dim of 1)."""
    b, s = tokens.shape
    assert b % microbatches == 0, "batch must split evenly into microbatches"
    mb = b // microbatches
    tok_mb = tokens.reshape(microbatches, mb, s)
    positions = jnp.arange(s, dtype=jnp.int32)[None].repeat(mb, axis=0)
    mask = positions[:, :, None] >= positions[:, None, :]
    stage = jax.lax.axis_index(axis)
    local = {w: stacked[w][0] for w in _LAYER_WEIGHTS}  # [lps, ...]
    perm = tuple((i, i + 1) for i in range(stages - 1))
    ticks = microbatches + stages - 1

    def tick(recv, t):
        # Stage 0 ingests microbatch t (clamped during the drain phase);
        # later stages consume what the previous stage sent last tick.
        tok_in = tok_mb[jnp.clip(t, 0, microbatches - 1)]
        x0 = jnp.take(stacked["embed"], tok_in, axis=0)
        x = jnp.where(stage == 0, x0, recv)
        y = _stage_forward(local, x, positions, mask, config)
        send = jax.lax.ppermute(y, axis, perm)
        # The last stage finishes microbatch t-(S-1) at tick t.
        h = _rms_norm(y, stacked["final_norm"])
        logits = jnp.einsum("bsd,dv->bsv", h, stacked["lm_head"]).astype(jnp.float32)
        tok_out = tok_mb[jnp.clip(t - (stages - 1), 0, microbatches - 1)]
        logp = jax.nn.log_softmax(logits[:, :-1])
        nll = -jnp.take_along_axis(logp, tok_out[:, 1:][..., None], axis=-1)[..., 0]
        valid = jnp.logical_and(t >= stages - 1, stage == stages - 1)
        return send, jnp.where(valid, nll.sum(), 0.0)

    init = jnp.zeros((mb, s, config.dim), dtype=config.dtype)
    # The carry flows through ppermute (varying over pp in shard_map's
    # manual-axes typing); the zero init must carry the same type.
    init = _pcast_varying(init, axis)
    _, sums = jax.lax.scan(tick, init, jnp.arange(ticks))
    total = jax.lax.psum(sums.sum(), axis)  # only the last stage contributes
    return total / (b * (s - 1))


def make_pp_train_step(mesh: Mesh, config: LlamaConfig, stages: int, microbatches: int):
    """Build a jitted pipeline training step over `mesh` (must carry a "pp"
    axis of size `stages`). Returns (step, shard_params): `shard_params`
    places stage-stacked params (stack_stage_params) onto the mesh; `step`
    is (stacked, tokens) -> (new_stacked, loss) with SGD, gradients flowing
    back through the inter-stage permutes."""
    pp_size = mesh.shape.get("pp")
    if pp_size != stages:
        raise ValueError(
            f"mesh 'pp' axis has {pp_size} devices but stages={stages}; a "
            "mismatch otherwise fails deep inside shard_map with an opaque "
            "IndexError"
        )
    specs = {w: P("pp") for w in _LAYER_WEIGHTS}
    specs.update({name: P() for name in _SHARED})

    def shard_params(stacked):
        return {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in stacked.items()
        }

    inner = shard_map(
        functools.partial(
            pp_loss_fn, config=config, stages=stages, microbatches=microbatches
        ),
        mesh=mesh,
        in_specs=(specs, P()),
        out_specs=P(),
    )

    @jax.jit
    def step(stacked, tokens, lr=1e-3):
        loss, grads = jax.value_and_grad(lambda p: inner(p, tokens))(stacked)
        new = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), stacked, grads)
        return new, loss

    return step, shard_params
