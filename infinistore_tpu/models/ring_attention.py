"""Ring attention: context parallelism for long sequences over an "sp" axis.

Sequences too long for one device's HBM shard across the mesh: each device
holds a contiguous sequence chunk of Q, K, V. Attention then needs every
(q, k) pair, so K/V chunks ROTATE around the ring with `lax.ppermute` while
each device accumulates its Q-chunk's attention online (flash-attention's
numerically-safe running max/denominator), one neighbor hop per step —
bandwidth-optimal: every byte of K/V crosses each ICI link exactly once, and
XLA overlaps the permute with the local attention compute.

The store connection: long-context prefill runs under exactly this sharding,
and its KV blocks stream to the store per device shard (each host's
connection carries its sequence chunk — the layerwise writer does not care
which parallelism produced the blocks). The reference has no compute at all
(SURVEY.md §5.7: the store serves engines that do SP; this module is the
engine-side piece so the dryrun can exercise the full pattern).

Correctness oracle: equals dense softmax attention on the gathered sequence
to float tolerance (tested, causal and full).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax: the experimental home (see paged_attention)
    # check_rep=False: the scan carry's replication typing needs the explicit
    # ``pcast`` only newer jax understands (see ``_pcast_varying``); the old
    # checker can't see it and rejects the gradient path's carry.
    from jax.experimental.shard_map import shard_map as _esm

    shard_map = functools.partial(_esm, check_rep=False)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _axis_size(axis: str) -> int:
    """Static mesh-axis size inside shard_map. ``jax.lax.axis_size`` where
    the jax is new enough; older jax has no such helper but statically
    folds a ``psum`` of a Python constant, so ``psum(1, axis)`` is the
    size as a plain int there too (``range``/``perm`` below need it
    static)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def _pcast_varying(x, axis: str):
    """``jax.lax.pcast(..., to="varying")`` where the jax has explicit
    varying-axes typing; older shard_map treats every value as varying
    already, so the cast is an identity there."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return x


def _ring_attention_local(
    q: jax.Array,  # [B, S_loc, H, D] this shard's query chunk
    k: jax.Array,  # [B, S_loc, H, D] this shard's key chunk (will rotate)
    v: jax.Array,  # [B, S_loc, H, D]
    axis: str,
    causal: bool,
) -> jax.Array:
    ring = _axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, s_loc, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q32 = q.astype(jnp.float32)
    q_pos = rank * s_loc + jnp.arange(s_loc)

    # Rotate so every chunk visits every device: after step i this shard
    # holds the chunk originating at rank - i (mod ring).
    perm = tuple((i, (i + 1) % ring) for i in range(ring))

    def step(carry, i):
        m, l, o, k_cur, v_cur = carry
        src = (rank - i) % ring
        k_pos = src * s_loc + jnp.arange(s_loc)
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                q32,
                k_cur.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            )
            * scale
        )
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [S_loc, S_loc] global
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        # exp(-inf - -inf) guards: fully-masked rows keep m at -inf; the
        # correction for them is defined as 1 (no prior mass to rescale).
        corr = jnp.where(jnp.isneginf(m_new), 1.0, jnp.exp(m - m_new))
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd",
            p,
            v_cur.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST,
        )
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (m_new, l, o, k_nxt, v_nxt), None

    m0 = jnp.full((b, h, s_loc), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), dtype=jnp.float32)
    o0 = jnp.zeros((b, h, s_loc, d), dtype=jnp.float32)
    # The accumulators mix with per-shard data (varying over sp in
    # shard_map's manual-axes typing); their zero inits must match.
    m0, l0, o0 = (_pcast_varying(x, axis) for x in (m0, l0, o0))
    (m, l, o, _, _), _ = jax.lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(ring)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, S_loc, H, D]


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "causal"))
def ring_attention(
    q: jax.Array,  # [B, S, H, D], S sharded over `axis`
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention: inputs/outputs sharded [B, S@sp, H, D].

    K/V head counts must equal Q's (repeat GQA heads before the call). The
    output keeps the input sharding — downstream per-token ops (FFN, norm)
    stay sequence-parallel with no resharding.
    """
    spec = P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return fn(*(jax.device_put(x, sharding) for x in (q, k, v)))


def dense_attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """The oracle: plain softmax attention over the full sequence."""
    d = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        s = q.shape[1]
        cm = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        scores = jnp.where(cm[None, None], scores, -jnp.inf)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bhqd",
        p,
        v.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
