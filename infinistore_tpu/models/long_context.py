"""Long-context prefill under sequence parallelism (ring attention inside).

The full transformer forward with the SEQUENCE sharded over an "sp" mesh
axis: each device embeds and projects only its token chunk, attention runs
as ring attention (K/V rotating, online softmax — ring_attention.py), and
the per-token ops (norms, FFN, logits) stay local — no resharding anywhere.
The outputs are exactly what the store ingests from a long-context engine:
per-layer K/V for the local token chunk, which each host's LayerwiseKVWriter
streams under its own connection (SURVEY.md §5.7: the store serves engines
that do SP; this is the engine side, end to end).

Exactness: logits and every layer's K/V equal the dense single-device
forward to float tolerance (tested) — the sharding changes the schedule,
never the math.
"""

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax: the experimental home (see paged_attention)
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import LlamaConfig, Params, _rms_norm, _rope
from .ring_attention import _axis_size, _ring_attention_local


def _local_forward(params, tokens, config: LlamaConfig, axis: str):
    """Runs INSIDE shard_map: tokens [B, S_loc] is this shard's chunk."""
    ring = _axis_size(axis)
    rank = jax.lax.axis_index(axis)
    b, s_loc = tokens.shape
    positions = (rank * s_loc + jnp.arange(s_loc, dtype=jnp.int32))[None].repeat(
        b, axis=0
    )
    x = jnp.take(params["embed"], tokens, axis=0)
    groups = config.n_heads // config.n_kv_heads
    kvs: List[Tuple[jax.Array, jax.Array]] = []
    for layer in range(config.n_layers):
        pre = f"l{layer}."
        h = _rms_norm(x, params[pre + "attn_norm"])
        q = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, params[pre + "wv"])
        q = _rope(q, positions, config.rope_theta)
        k = _rope(k, positions, config.rope_theta)
        kvs.append((k, v))
        attn = _ring_attention_local(
            q,
            jnp.repeat(k, groups, axis=2),
            jnp.repeat(v, groups, axis=2),
            axis=axis,
            causal=True,
        )
        x = x + jnp.einsum("bshk,hkd->bsd", attn, params[pre + "wo"])
        h = _rms_norm(x, params[pre + "ffn_norm"])
        gate_up = jnp.einsum("bsd,dcf->bscf", h, params[pre + "w_gate_up"])
        ffn = jax.nn.silu(gate_up[:, :, 0]) * gate_up[:, :, 1]
        x = x + jnp.einsum("bsf,fd->bsd", ffn, params[pre + "w_down"])
    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"])
    flat_kv = tuple(t for kv in kvs for t in kv)
    return (logits,) + flat_kv


@functools.partial(jax.jit, static_argnames=("config", "mesh", "axis"))
def prefill_ring(
    params: Params,
    tokens: jax.Array,  # [B, S] int32, S % sp_size == 0
    config: LlamaConfig,
    *,
    mesh: Mesh,
    axis: str = "sp",
):
    """Sequence-parallel prefill. Returns (logits, [(k, v) per layer]) with
    sequence dims sharded over `axis`: logits [B, S@sp, V], k/v
    [B, S@sp, n_kv_heads, head_dim]. Each shard's K/V chunk is what that
    host streams to the store (reshape to token blocks + LayerwiseKVWriter);
    dense (non-MoE) configs only."""
    if config.n_experts > 0:
        raise ValueError("prefill_ring covers the dense FFN config")
    seq_spec = P(None, axis)
    out_spec = P(None, axis, None)
    kv_spec = P(None, axis, None, None)
    n_out = 1 + 2 * config.n_layers
    fn = shard_map(
        functools.partial(_local_forward, config=config, axis=axis),
        mesh=mesh,
        in_specs=(P(), seq_spec),
        out_specs=(out_spec,) + (kv_spec,) * (n_out - 1),
    )
    tokens = jax.device_put(tokens, NamedSharding(mesh, seq_spec))
    outs = fn(params, tokens)
    logits = outs[0]
    kvs = [(outs[1 + 2 * l], outs[2 + 2 * l]) for l in range(config.n_layers)]
    return logits, kvs
