"""Demo model family: a small Llama-style transformer with a paged KV cache.

The reference ships no model code — it serves engines like vLLM through
LMCache (reference README.md:22). This package plays that engine's role
for the TPU build: a real (if small) paged-KV transformer whose prefill/decode
steps produce and consume the exact block layout the store moves, so the
prefill->decode disaggregation flow (BASELINE.md config 5) can run end-to-end
in tests and benchmarks, and the driver's graft entry has a jittable flagship
step to compile.
"""

from .llama import (
    LlamaConfig,
    decode_step,
    decode_step_batched,
    decode_wave_layer,
    embed_prompt,
    embed_wave,
    lm_logits,
    prefill_layer,
    verify_step_batched,
    verify_step_ragged,
    init_params,
    loss_fn,
    prefill,
    prefill_continue,
    speculative_verify,
    train_step,
)

__all__ = [
    "LlamaConfig",
    "init_params",
    "prefill",
    "prefill_continue",
    "prefill_layer",
    "embed_prompt",
    "embed_wave",
    "lm_logits",
    "decode_wave_layer",
    "speculative_verify",
    "decode_step",
    "decode_step_batched",
    "verify_step_batched",
    "verify_step_ragged",
    "loss_fn",
    "train_step",
]
