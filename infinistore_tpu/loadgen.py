"""Trace-driven serving load generator (docs/serving_load.md, ROADMAP-6).

Every perf receipt before this module graded a uniform synthetic
workload, but wave economics are decided by SKEW: Ragged Paged Attention
is an argument about not paying for the skewed tail, and Zipf working
sets are the access shape millions of real users actually produce. This
module emits a deterministic, seeded, REPLAYABLE trace of an open-loop
serving workload so the engine harness, ``bench.py``'s serving leg, the
``benchmark.py --trace`` CLI mode, and the ``DisaggHarness`` all grade
against one traffic shape:

- **Zipf prefix popularity** over a synthetic million-user population:
  each request draws a shared-prefix family with P(rank k) proportional
  to 1/k^s — the head families dominate exactly as production prefix
  caches observe (system prompts, few-shot templates).
- **Log-normal lengths with a heavy tail**: prompt and output lengths
  are log-normal; a configurable outlier fraction multiplies the draw
  into the tail, and requests past ``bg_outlier_blocks`` total blocks
  are tagged ``PRIORITY_BACKGROUND`` (the QoS class the skew-aware wave
  flush policy's starvation bound keys on).
- **Diurnal rate curve + burst storms**: the open-loop arrival rate is
  ``base_rate_rps * diurnal(t) * burst(t)`` — a sinusoidal day cycle
  with storm windows that multiply the rate — sampled by thinning a
  homogeneous Poisson process, so arrivals stay deterministic per seed.
- **Mixed prefill/decode + shared-prefix reuse**: a configurable
  fraction of requests is prefill-only (``gen_tokens == 0``), and
  ``Trace.prompts`` materializes token lists as family prefix + unique
  suffix, so replay exercises real prefix hits.

The trace is a plain JSON document (``Trace.to_json``/``from_json``;
schema in docs/serving_load.md) — the replay side never re-runs the
generator, so a saved trace reproduces a result bit-for-bit later.

Concurrency (ITS-R audit): none. Generation and replay are pure
single-threaded functions over a seeded ``numpy`` Generator; the module
spawns no threads, holds no locks, and shares no mutable state — the
consumers (engine harness, bench legs) each own their Trace instance.
"""

import json
import math
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from .wire import PRIORITY_BACKGROUND, PRIORITY_FOREGROUND

TRACE_VERSION = 1

# Named workload shapes (docs/serving_load.md). "skewed" is the default
# serving mix the bench leg grades the flush policy under; "uniform" is
# the null shape (no skew — a policy regression detector); "outlier_flood"
# keeps a permanent stream of heavy background outliers in flight — the
# starvation-bound leg (aging escapes must fire, never stranding).
PRESETS: Dict[str, dict] = {
    "skewed": dict(
        n_prefixes=64, zipf_s=1.2, base_rate_rps=200.0,
        prompt_blocks_mu=0.3, prompt_blocks_sigma=0.6,
        gen_tokens_mu=2.0, gen_tokens_sigma=0.8,
        outlier_frac=0.08, outlier_mult=4.0, bg_outlier_blocks=6,
        diurnal_amplitude=0.5, burst_prob_per_s=0.05, burst_mult=4.0,
        prefill_only_frac=0.3,
    ),
    "uniform": dict(
        n_prefixes=64, zipf_s=0.0, base_rate_rps=200.0,
        prompt_blocks_mu=0.7, prompt_blocks_sigma=0.0,
        gen_tokens_mu=2.0, gen_tokens_sigma=0.0,
        outlier_frac=0.0, outlier_mult=1.0, bg_outlier_blocks=10 ** 9,
        diurnal_amplitude=0.0, burst_prob_per_s=0.0, burst_mult=1.0,
        prefill_only_frac=0.3,
    ),
    "outlier_flood": dict(
        n_prefixes=16, zipf_s=1.2, base_rate_rps=200.0,
        prompt_blocks_mu=0.7, prompt_blocks_sigma=0.4,
        gen_tokens_mu=2.0, gen_tokens_sigma=0.6,
        outlier_frac=0.5, outlier_mult=4.0, bg_outlier_blocks=3,
        diurnal_amplitude=0.0, burst_prob_per_s=0.0, burst_mult=1.0,
        prefill_only_frac=0.0,
    ),
}


@dataclass(frozen=True)
class TraceRequest:
    """One open-loop arrival. Lengths are in engine units — prompt BLOCKS
    (complete blocks, the harness admission contract) and generated
    TOKENS — so the same trace replays against any ``block_tokens``."""

    t_s: float          # arrival offset from trace start (open loop)
    user: int           # synthetic user id (million-user population)
    prefix_id: int      # shared-prefix family (Zipf-popular rank)
    prefix_blocks: int  # blocks of the family's shared prefix
    prompt_blocks: int  # total prompt blocks (>= prefix_blocks)
    gen_tokens: int     # 0 = prefill-only request
    priority: int       # wire.PRIORITY_* (heavy-tail outliers ride BACKGROUND)
    burst: bool         # arrived inside a burst storm window


@dataclass
class Trace:
    """A replayable workload: metadata + the arrival list, JSON round-
    trippable (``save``/``load``) so a graded run can be reproduced from
    the artifact alone."""

    seed: int
    duration_s: float
    knobs: dict
    requests: List[TraceRequest] = field(default_factory=list)
    version: int = TRACE_VERSION

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "seed": self.seed,
            "duration_s": self.duration_s,
            "knobs": self.knobs,
            "requests": [asdict(r) for r in self.requests],
        })

    @classmethod
    def from_json(cls, text: str) -> "Trace":
        doc = json.loads(text)
        if doc.get("version") != TRACE_VERSION:
            raise ValueError(
                f"unsupported trace version {doc.get('version')!r} "
                f"(want {TRACE_VERSION})"
            )
        return cls(
            seed=doc["seed"],
            duration_s=doc["duration_s"],
            knobs=doc["knobs"],
            requests=[TraceRequest(**r) for r in doc["requests"]],
            version=doc["version"],
        )

    def save(self, path: str):
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_json(f.read())

    # -- materialization ----------------------------------------------------

    def prompts(
        self,
        block_tokens: int,
        vocab: int = 128,
        max_blocks: Optional[int] = None,
    ) -> List[List[int]]:
        """Deterministic token lists for every request: the family's
        shared prefix (same bytes for every request in the family — the
        prefix-cache hit surface) followed by a request-unique suffix.
        ``max_blocks`` clamps each prompt to the replay harness's
        per-request table size (prefix first, suffix truncated)."""
        out: List[List[int]] = []
        for i, r in enumerate(self.requests):
            n_blocks = r.prompt_blocks
            pre_blocks = min(r.prefix_blocks, n_blocks)
            if max_blocks is not None:
                n_blocks = min(n_blocks, max_blocks)
                pre_blocks = min(pre_blocks, n_blocks)
            pre = np.random.default_rng(
                (self.seed * 1_000_003 + r.prefix_id) & 0x7FFFFFFF
            ).integers(0, vocab, size=pre_blocks * block_tokens)
            suf = np.random.default_rng(
                (self.seed * 1_000_003 + 7_777_777 + i) & 0x7FFFFFFF
            ).integers(0, vocab, size=(n_blocks - pre_blocks) * block_tokens)
            out.append(np.concatenate([pre, suf]).astype(int).tolist())
        return out


def _zipf_cdf(n: int, s: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return np.cumsum(w / w.sum())


def generate(
    seed: int = 0,
    duration_s: float = 2.0,
    users: int = 1_000_000,
    n_prefixes: int = 64,
    zipf_s: float = 1.2,
    base_rate_rps: float = 200.0,
    prompt_blocks_mu: float = 0.7,
    prompt_blocks_sigma: float = 0.6,
    max_prompt_blocks: int = 8,
    max_prefix_blocks: int = 3,
    gen_tokens_mu: float = 2.0,
    gen_tokens_sigma: float = 0.8,
    max_gen_tokens: int = 32,
    outlier_frac: float = 0.08,
    outlier_mult: float = 4.0,
    bg_outlier_blocks: int = 4,
    diurnal_amplitude: float = 0.5,
    diurnal_period_s: float = 1.0,
    burst_prob_per_s: float = 0.05,
    burst_len_s: float = 0.1,
    burst_mult: float = 4.0,
    prefill_only_frac: float = 0.3,
    max_requests: int = 100_000,
) -> Trace:
    """Generate a trace (see module docstring for the model). Everything
    is driven by ONE ``numpy`` Generator seeded from ``seed`` — the same
    seed and knobs produce the identical trace, byte for byte (tested).

    ``diurnal_period_s`` is the day length in TRACE seconds — traces are
    replayed time-scaled, so a 1 s "day" grades the same shape a 86400 s
    one would without a day-long bench. ``max_requests`` is a hard cap
    (rate knobs cannot runaway-allocate)."""
    rng = np.random.default_rng(seed)
    knobs = dict(
        users=users, n_prefixes=n_prefixes, zipf_s=zipf_s,
        base_rate_rps=base_rate_rps,
        prompt_blocks_mu=prompt_blocks_mu,
        prompt_blocks_sigma=prompt_blocks_sigma,
        max_prompt_blocks=max_prompt_blocks,
        max_prefix_blocks=max_prefix_blocks,
        gen_tokens_mu=gen_tokens_mu, gen_tokens_sigma=gen_tokens_sigma,
        max_gen_tokens=max_gen_tokens,
        outlier_frac=outlier_frac, outlier_mult=outlier_mult,
        bg_outlier_blocks=bg_outlier_blocks,
        diurnal_amplitude=diurnal_amplitude,
        diurnal_period_s=diurnal_period_s,
        burst_prob_per_s=burst_prob_per_s, burst_len_s=burst_len_s,
        burst_mult=burst_mult, prefill_only_frac=prefill_only_frac,
    )
    # Burst storm windows: a Bernoulli draw per second-of-trace opens a
    # window of burst_len_s at burst_mult x rate.
    storms = []
    t = 0.0
    while t < duration_s:
        if burst_prob_per_s > 0 and rng.random() < burst_prob_per_s:
            storms.append((t, t + burst_len_s))
        t += 1.0

    def in_storm(ts: float) -> bool:
        return any(a <= ts < b for a, b in storms)

    def rate(ts: float) -> float:
        r = base_rate_rps * (
            1.0 + diurnal_amplitude
            * math.sin(2.0 * math.pi * ts / diurnal_period_s)
        )
        if in_storm(ts):
            r *= burst_mult
        return max(r, 0.0)

    # Thinned Poisson arrivals: candidates at the max rate, accepted with
    # probability rate(t)/rate_max — the standard way to keep a time-
    # varying arrival process exactly reproducible from one rng stream.
    rate_max = base_rate_rps * (1.0 + abs(diurnal_amplitude)) * max(
        burst_mult if storms else 1.0, 1.0
    )
    zipf = _zipf_cdf(n_prefixes, zipf_s)
    # Per-family shared-prefix depth (deterministic in the family rank).
    prefix_depth = rng.integers(1, max_prefix_blocks + 1, size=n_prefixes)
    requests: List[TraceRequest] = []
    t = 0.0
    while len(requests) < max_requests:
        t += rng.exponential(1.0 / rate_max)
        if t >= duration_s:
            break
        if rng.random() >= rate(t) / rate_max:
            continue
        fam = int(np.searchsorted(zipf, rng.random()))
        pre = int(prefix_depth[fam])
        blocks = pre + int(round(rng.lognormal(
            prompt_blocks_mu, prompt_blocks_sigma
        )))
        gen = max(1, int(round(rng.lognormal(gen_tokens_mu, gen_tokens_sigma))))
        if rng.random() < outlier_frac:
            # The heavy tail: a multiplied draw, not a wider sigma — the
            # tail mass is a knob independent of the body's shape.
            blocks = int(blocks * outlier_mult)
            gen = int(gen * outlier_mult)
        blocks = min(max(blocks, 1), max_prompt_blocks)
        gen = min(gen, max_gen_tokens)
        if rng.random() < prefill_only_frac:
            gen = 0
        prio = (
            PRIORITY_BACKGROUND if blocks >= bg_outlier_blocks
            else PRIORITY_FOREGROUND
        )
        requests.append(TraceRequest(
            t_s=round(float(t), 6),
            user=int(rng.integers(0, users)),
            prefix_id=fam,
            prefix_blocks=min(pre, blocks),
            prompt_blocks=blocks,
            gen_tokens=gen,
            priority=prio,
            burst=in_storm(t),
        ))
    return Trace(seed=seed, duration_s=duration_s, knobs=knobs,
                 requests=requests)


def preset(name: str, seed: int = 0, **overrides) -> Trace:
    """Generate one of the named PRESETS shapes (docs/serving_load.md);
    ``overrides`` patch individual knobs (e.g. a shorter duration_s)."""
    if name not in PRESETS:
        raise ValueError(
            f"unknown preset {name!r} (have {sorted(PRESETS)})"
        )
    kw = dict(PRESETS[name])
    kw.update(overrides)
    return generate(seed=seed, **kw)


async def replay(
    trace: Trace,
    harness,
    time_scale: float = 0.0,
    vocab: Optional[int] = None,
    concurrency: int = 16,
):
    """Replay a trace through a ``ContinuousBatchingHarness``: each
    request's ``run_request(prompt, gen_tokens, priority)`` fires at its
    arrival offset scaled by ``time_scale`` (0.0 = as fast as admission
    allows, preserving arrival ORDER — the closed-loop mode bench rounds
    use so wall time measures the engine, not the trace clock).
    Per-request failures surface as the exception objects in the
    returned list — a replay never hides a wrong-bytes verdict.

    Returns the per-request ``RequestStats`` in trace order."""
    import asyncio

    prompts = trace.prompts(
        harness.config.block_tokens,
        vocab=vocab if vocab is not None else harness.config.vocab,
        max_blocks=harness.max_req_blocks,
    )
    sem = asyncio.Semaphore(concurrency)

    async def one(req: TraceRequest, prompt: List[int]):
        if time_scale > 0:
            await asyncio.sleep(req.t_s * time_scale)
        gen = req.gen_tokens
        bt = harness.config.block_tokens
        # Clamp generation to the per-request table like prompts are.
        room = harness.max_req_blocks * bt - len(prompt)
        gen = min(gen, max(room, 0))
        async with sem:
            return await harness.run_request(
                prompt, gen_tokens=gen, priority=req.priority
            )

    return await asyncio.gather(
        *(one(r, p) for r, p in zip(trace.requests, prompts)),
        return_exceptions=True,
    )
