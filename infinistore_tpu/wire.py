"""Python mirror of the native wire protocol (native/include/its/protocol.h).

The client/server data plane lives in C++; this module exists for (a) building
the packed key blobs passed across the ctypes boundary, and (b) protocol unit
tests that check the Python and C++ encoders agree byte-for-byte — coverage the
reference lacks entirely (SURVEY.md §4: no protocol unit tests).
"""

import struct
from dataclasses import dataclass, field
from typing import List, Tuple

MAGIC = 0x49545055  # "ITPU" little-endian
MAX_BODY_SIZE = 4 << 20

# Op codes (native protocol.h Op).
OP_PUT_BATCH = ord("W")
OP_GET_BATCH = ord("R")
OP_TCP_PUT = ord("P")
OP_TCP_GET = ord("G")
OP_CHECK_EXIST = ord("E")
OP_MATCH_LAST_IDX = ord("M")
OP_DELETE_KEYS = ord("D")
OP_STAT = ord("S")
# Same-host shm fast path (native protocol.h: allocate-then-commit writes,
# locate-then-release reads; payload never touches the socket).
OP_SHM_HELLO = ord("H")
OP_PUT_ALLOC = ord("p")
OP_PUT_COMMIT = ord("c")
OP_GET_LOC = ord("g")
OP_RELEASE = ord("r")
# One-RTT segment path (native protocol.h: server pulls puts out of / pushes
# gets into a client-registered shm segment).
OP_REG_SEGMENT = ord("B")
OP_PUT_FROM = ord("F")
OP_GET_INTO = ord("I")
# Descriptor-ring data plane (docs/descriptor_ring.md): batched segment ops
# post as fixed-slot descriptors in a client-created shm ring; the socket
# carries only the attach handshake and doze/wake doorbells.
OP_RING_ATTACH = ord("Q")
OP_RING_DOORBELL = ord("q")

# Status codes (reference src/protocol.h:55-62).
# STATUS_RING_EVENT is the unsolicited server->client completion-ring
# doorbell frame — 1xx so it can never collide with a real response status.
STATUS_RING_EVENT = 100
STATUS_OK = 200
STATUS_TASK_ACCEPTED = 202
STATUS_INVALID_REQ = 400
STATUS_KEY_NOT_FOUND = 404
STATUS_RETRY = 408
STATUS_INTERNAL = 500
STATUS_UNAVAILABLE = 503
STATUS_OUT_OF_MEMORY = 507
STATUS_OOM = STATUS_OUT_OF_MEMORY
# Present-but-unpromotable spilled key: "cold but alive" — data survives one
# tier down; distinct from 507 (allocation exhaustion) and 404 (absent).
STATUS_COLD_TIER = 512

_REQ_HEADER = struct.Struct("<IBI")  # magic, op, body_size (9 bytes)
_RESP_HEADER = struct.Struct("<IIQ")  # status, body_size, payload_size (16 bytes)

# ---------------------------------------------------------------------------
# Descriptor-ring slot layout (docs/descriptor_ring.md). These structs are
# MEMORY-MAPPED by both processes, so field NAMES and widths are protocol
# surface exactly like the packed wire headers: the formats below are held
# in lockstep with native RingCtrl/RingSlot/RingCqe by the wire-drift
# checker (ITS-W004 widths, ITS-W005 named-field order via RING_LAYOUTS).
# ---------------------------------------------------------------------------

RING_MAGIC = 0x52535449  # "ITSR" little-endian
RING_VERSION = 1
RING_SQ_SLOTS = 64  # default submission-slot count (ClientConfig.ring_slots)
RING_META_STRIDE = 128 << 10  # per-SQ-slot descriptor-body capacity
RING_CTRL_SPAN = 4096  # RingCtrl's reserved span at the segment head

_RING_CTRL = struct.Struct("<IIIIIIIIQQQQII")  # 72 bytes
_RING_SLOT = struct.Struct("<QQIBBH")  # 24 bytes
_RING_CQE = struct.Struct("<QQQII")  # 32 bytes
_RING_BATCH_HDR = struct.Struct("<HH")  # 4 bytes
_RING_BATCH_ENTRY = struct.Struct("<IBBH")  # 8 bytes

# Multi-op batch slots: a slot with RING_SLOT_FLAG_BATCH in its flags packs
# a whole coalesced flush into its meta arena — RingBatchHdr, then count x
# (RingBatchEntry + that op's SegBatchMeta bytes). The slot token is the
# base of a contiguous token group; op i completes under token base+i.
RING_SLOT_FLAG_BATCH = 0x1
RING_BATCH_MAX_OPS = 64

# Named-field twins of the native ring structs. Same-width field swaps are
# invisible to a width-sequence diff (ITS-W004) but fatal for shared memory
# — the checker's ITS-W005 compares these (name, width) sequences against
# the packed C++ declarations field by field.
RING_LAYOUTS = {
    "RingCtrl": (
        ("magic", "u32"),
        ("version", "u32"),
        ("sq_slots", "u32"),
        ("cq_slots", "u32"),
        ("slot_bytes", "u32"),
        ("cqe_bytes", "u32"),
        ("meta_stride", "u32"),
        ("flags", "u32"),
        ("sq_tail", "u64"),
        ("sq_head", "u64"),
        ("cq_tail", "u64"),
        ("cq_head", "u64"),
        ("srv_waiting", "u32"),
        ("cli_waiting", "u32"),
    ),
    "RingSlot": (
        ("gen", "u64"),
        ("token", "u64"),
        ("meta_len", "u32"),
        ("op", "u8"),
        ("flags", "u8"),
        ("reserved", "u16"),
    ),
    "RingCqe": (
        ("gen", "u64"),
        ("token", "u64"),
        ("bytes", "u64"),
        ("status", "u32"),
        ("flags", "u32"),
    ),
    "RingBatchHdr": (
        ("count", "u16"),
        ("reserved", "u16"),
    ),
    "RingBatchEntry": (
        ("meta_len", "u32"),
        ("op", "u8"),
        ("flags", "u8"),
        ("reserved", "u16"),
    ),
}


def ring_batch_encode(ops) -> bytes:
    """Pack a batch slot's meta-arena bytes: RingBatchHdr + per-op
    (RingBatchEntry + SegBatchMeta body). ``ops`` is a sequence of
    (op_code, body_bytes) pairs — the reference encoding the native
    client's ring_group_end mirrors, byte for byte (pinned by
    tests/test_ring.py's batch-layout golden)."""
    if not 1 <= len(ops) <= RING_BATCH_MAX_OPS:
        raise ValueError("batch op count out of range")
    parts = [_RING_BATCH_HDR.pack(len(ops), 0)]
    for op_code, body in ops:
        parts.append(_RING_BATCH_ENTRY.pack(len(body), op_code, 0, 0))
        parts.append(bytes(body))
    return b"".join(parts)


def _ring_align64(v: int) -> int:
    return (v + 63) & ~63


def ring_sq_off() -> int:
    """Submission-slot array offset inside a ring segment (native ring.h)."""
    return RING_CTRL_SPAN


def ring_cq_off(sq_slots: int) -> int:
    return ring_sq_off() + _ring_align64(sq_slots * _RING_SLOT.size)


def ring_meta_off(sq_slots: int, cq_slots: int) -> int:
    return ring_cq_off(sq_slots) + _ring_align64(cq_slots * _RING_CQE.size)


def ring_segment_bytes(sq_slots: int, cq_slots: int, meta_stride: int) -> int:
    return ring_meta_off(sq_slots, cq_slots) + sq_slots * meta_stride


def ring_ctrl_offset(fld: str) -> int:
    """Byte offset of a RingCtrl field — the tamper/inspection hook the ring
    tests use to poke cursors in a mapped segment from Python."""
    off = 0
    for name, prim in RING_LAYOUTS["RingCtrl"]:
        if name == fld:
            return off
        off += {"u8": 1, "u16": 2, "u32": 4, "u64": 8}[prim]
    raise KeyError(fld)

# Two-class QoS service model (docs/qos.md). FOREGROUND is the default and
# encodes as NO wire bytes (the priority-off path stays byte-identical);
# BACKGROUND rides an optional trailing tag byte on the batch/segment
# metadata bodies, which pre-QoS decoders never read (the body length is
# explicit) and pre-QoS encoders never produce.
PRIORITY_FOREGROUND = 0
PRIORITY_BACKGROUND = 1

# End-to-end op tracing (docs/observability.md): a per-op trace context —
# u64 trace id + u64 parent span id — rides BatchMeta/SegBatchMeta as a
# SECOND trailing optional extension AFTER the QoS priority byte. An
# untraced op (trace_id == 0, the default) appends nothing and stays
# byte-identical to the pre-trace format; a traced op must therefore also
# emit the priority byte (even FOREGROUND's 0) so the decoder's
# read-while-bytes-remain walk stays unambiguous. TRACE_ID_NONE is the
# wire's "untraced" sentinel — real trace ids are never zero
# (tracing._new_id).
TRACE_ID_NONE = 0


def qos_kwargs(conn, priority: int) -> dict:
    """Kwargs for tagging a batched op on ``conn`` with ``priority``.

    Empty when the op is FOREGROUND (untagged — the default path must stay
    byte-identical AND signature-compatible with priority-unaware
    connection stand-ins) or when ``conn`` does not advertise ``QOS_AWARE``
    (a tag it cannot carry is dropped, not TypeError'd — QoS degrades to
    FIFO, never breaks the data plane)."""
    if priority and getattr(conn, "QOS_AWARE", False):
        return {"priority": priority}
    return {}


def pack_req_header(op: int, body_size: int) -> bytes:
    return _REQ_HEADER.pack(MAGIC, op, body_size)


def unpack_req_header(data: bytes) -> Tuple[int, int]:
    magic, op, body_size = _REQ_HEADER.unpack(data[: _REQ_HEADER.size])
    if magic != MAGIC:
        raise ValueError("bad magic")
    return op, body_size


def pack_resp_header(status: int, body_size: int, payload_size: int) -> bytes:
    return _RESP_HEADER.pack(status, body_size, payload_size)


def unpack_resp_header(data: bytes) -> Tuple[int, int, int]:
    return _RESP_HEADER.unpack(data[: _RESP_HEADER.size])


def encode_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) > 0xFFFF:
        raise ValueError("key too long")
    return struct.pack("<H", len(b)) + b


def encode_keys_blob(keys: List[str]) -> bytes:
    """Packed (u16 len, bytes) entries — the ctypes boundary format and the
    wire string-list element encoding (WireWriter::str)."""
    return b"".join(encode_str(k) for k in keys)


def encode_str_list(keys: List[str]) -> bytes:
    return struct.pack("<I", len(keys)) + encode_keys_blob(keys)


class Reader:
    def __init__(self, data: bytes):
        self._d = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._d):
            raise ValueError("wire body truncated")
        out = self._d[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def str(self) -> str:
        return self._take(self.u16()).decode("utf-8")

    def str_list(self) -> List[str]:
        return [self.str() for _ in range(self.u32())]

    @property
    def done(self) -> bool:
        return self._pos == len(self._d)


@dataclass
class BatchMeta:
    """Batched block metadata (native BatchMeta; reference RemoteMetaRequest,
    reference src/meta_request.fbs:2-8). ``priority`` is the QoS class tag:
    FOREGROUND (0) encodes nothing — byte-identical to the pre-QoS format —
    and BACKGROUND appends one trailing byte."""

    block_size: int = 0
    keys: List[str] = field(default_factory=list)
    priority: int = PRIORITY_FOREGROUND
    # Trace context extension (second trailing optional group — see
    # TRACE_ID_NONE above): 0/0 encodes nothing.
    trace_id: int = TRACE_ID_NONE
    trace_parent: int = 0

    def encode(self) -> bytes:
        out = struct.pack("<I", self.block_size) + encode_str_list(self.keys)
        if self.priority or self.trace_id:
            out += struct.pack("<B", self.priority)
        if self.trace_id:
            out += struct.pack("<QQ", self.trace_id, self.trace_parent)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "BatchMeta":
        r = Reader(data)
        m = cls(block_size=r.u32(), keys=r.str_list())
        if not r.done:
            m.priority = r.u8()
        if not r.done:
            m.trace_id = r.u64()
            m.trace_parent = r.u64()
        return m


@dataclass
class TcpPutMeta:
    key: str = ""
    value_length: int = 0

    def encode(self) -> bytes:
        return encode_str(self.key) + struct.pack("<Q", self.value_length)

    @classmethod
    def decode(cls, data: bytes) -> "TcpPutMeta":
        r = Reader(data)
        return cls(key=r.str(), value_length=r.u64())


@dataclass
class TicketMeta:
    """Shm fast-path ticket (native TicketMeta: PutCommit / Release)."""

    ticket: int = 0

    def encode(self) -> bytes:
        return struct.pack("<Q", self.ticket)

    @classmethod
    def decode(cls, data: bytes) -> "TicketMeta":
        return cls(ticket=Reader(data).u64())


@dataclass
class ShmLocResp:
    """PutAlloc/GetLoc/ShmHello response body (native ShmLocResp):
    {ticket, locations, shm pool directory}."""

    ticket: int = 0
    locs: List[Tuple[int, int, int]] = field(default_factory=list)  # (pool, off, size)
    pools: List[Tuple[int, str, int]] = field(default_factory=list)  # (pool, name, size)

    def encode(self) -> bytes:
        out = [struct.pack("<QI", self.ticket, len(self.locs))]
        for pool_id, off, size in self.locs:
            out.append(struct.pack("<HQI", pool_id, off, size))
        out.append(struct.pack("<H", len(self.pools)))
        for pool_id, name, size in self.pools:
            out.append(struct.pack("<H", pool_id) + encode_str(name) + struct.pack("<Q", size))
        return b"".join(out)

    @classmethod
    def decode(cls, data: bytes) -> "ShmLocResp":
        r = Reader(data)
        m = cls(ticket=r.u64())
        for _ in range(r.u32()):
            m.locs.append((r.u16(), r.u64(), r.u32()))
        for _ in range(r.u16()):
            m.pools.append((r.u16(), r.str(), r.u64()))
        return m


@dataclass
class SegMeta:
    """Client shm segment registration (native SegMeta: RegSegment)."""

    seg_id: int = 0
    name: str = ""
    size: int = 0

    def encode(self) -> bytes:
        return struct.pack("<H", self.seg_id) + encode_str(self.name) + struct.pack(
            "<Q", self.size
        )

    @classmethod
    def decode(cls, data: bytes) -> "SegMeta":
        r = Reader(data)
        return cls(seg_id=r.u16(), name=r.str(), size=r.u64())


@dataclass
class RingMeta:
    """Descriptor-ring segment registration (native RingMeta: RingAttach).

    Only names the shm segment — the ring geometry lives in the mapped
    RingCtrl itself, single-sourced so the attach body can never drift
    from the control block."""

    name: str = ""
    size: int = 0

    def encode(self) -> bytes:
        return encode_str(self.name) + struct.pack("<Q", self.size)

    @classmethod
    def decode(cls, data: bytes) -> "RingMeta":
        r = Reader(data)
        return cls(name=r.str(), size=r.u64())


@dataclass
class SegBatchMeta:
    """One-RTT batched op against a registered segment (native SegBatchMeta:
    PutFrom / GetInto); block i lives at segment offset offsets[i].
    ``priority`` follows BatchMeta's optional-trailing-byte scheme."""

    block_size: int = 0
    seg_id: int = 0
    keys: List[str] = field(default_factory=list)
    offsets: List[int] = field(default_factory=list)
    priority: int = PRIORITY_FOREGROUND
    # Trace context extension (after the priority byte; see BatchMeta).
    trace_id: int = TRACE_ID_NONE
    trace_parent: int = 0

    def encode(self) -> bytes:
        out = [struct.pack("<IH", self.block_size, self.seg_id)]
        out.append(encode_str_list(self.keys))
        out.append(struct.pack("<I", len(self.offsets)))
        out.extend(struct.pack("<Q", off) for off in self.offsets)
        if self.priority or self.trace_id:
            out.append(struct.pack("<B", self.priority))
        if self.trace_id:
            out.append(struct.pack("<QQ", self.trace_id, self.trace_parent))
        return b"".join(out)

    @classmethod
    def decode(cls, data: bytes) -> "SegBatchMeta":
        r = Reader(data)
        m = cls(block_size=r.u32(), seg_id=r.u16(), keys=r.str_list())
        m.offsets = [r.u64() for _ in range(r.u32())]
        if not r.done:
            m.priority = r.u8()
        if not r.done:
            m.trace_id = r.u64()
            m.trace_parent = r.u64()
        return m


@dataclass
class ChunkDesc:
    """Descriptor for one contiguous slice of a split batched op — the
    work-stealing unit of the adaptive striped data plane
    (lib.StripedConnection): a batch of N blocks is broken into bounded
    descriptors on a shared queue and stripes pull them as they finish
    prior ones. ``start``/``count`` index the ORIGINAL batch's block list
    (contiguous, so each stripe's scatter/gather iovec runs stay long);
    ``seq`` orders descriptors for debugging/tracing. The wire protocol
    itself is unchanged — each pulled descriptor rides an ordinary batched
    op on its stripe — but the framing here is the canonical record (and
    the unit tests' contract) for anything that persists or ships a split
    plan, e.g. a cross-process scheduler or a replay trace."""

    seq: int = 0
    start: int = 0
    count: int = 0

    _STRUCT = struct.Struct("<IQI")

    def encode(self) -> bytes:
        return self._STRUCT.pack(self.seq, self.start, self.count)

    @classmethod
    def decode(cls, data: bytes) -> "ChunkDesc":
        if len(data) < cls._STRUCT.size:
            raise ValueError("wire body truncated")
        seq, start, count = cls._STRUCT.unpack(data[: cls._STRUCT.size])
        return cls(seq=seq, start=start, count=count)


def chunk_spans(n_blocks: int, quantum: int) -> List[ChunkDesc]:
    """Split an n-block batch into bounded contiguous chunk descriptors of
    at most ``quantum`` blocks each (the last may be shorter). The shared
    queue the striped scheduler's workers pull from is exactly this list."""
    if n_blocks < 0:
        raise ValueError("n_blocks must be >= 0")
    if quantum < 1:
        raise ValueError("quantum must be >= 1")
    return [
        ChunkDesc(seq=seq, start=start, count=min(quantum, n_blocks - start))
        for seq, start in enumerate(range(0, n_blocks, quantum))
    ]


@dataclass
class KeyMeta:
    key: str = ""

    def encode(self) -> bytes:
        return encode_str(self.key)

    @classmethod
    def decode(cls, data: bytes) -> "KeyMeta":
        return cls(key=Reader(data).str())


@dataclass
class KeyListMeta:
    keys: List[str] = field(default_factory=list)

    def encode(self) -> bytes:
        return encode_str_list(self.keys)

    @classmethod
    def decode(cls, data: bytes) -> "KeyListMeta":
        return cls(keys=Reader(data).str_list())
