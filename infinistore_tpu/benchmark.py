"""Benchmark CLI (reference infinistore/benchmark.py surface: N blocks x
block-size KB simulating --steps layers, RDMA-style async batched or TCP
single-key transfers, write/read MB/s report + data verification,
benchmark.py:53-271). numpy staging buffers replace torch CUDA tensors — on
TPU the client side stages in host DRAM (see infinistore_tpu.tpu for the
HBM<->host path).
"""

import argparse
import asyncio
import json
import time
import uuid

import numpy as np

from .config import TYPE_RDMA, TYPE_TCP, ClientConfig
from .lib import InfinityConnection, StripedConnection


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="infinistore-tpu-benchmark")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--service-port", type=int, default=22345)
    p.add_argument("--size", type=int, default=128, help="total MB to transfer")
    p.add_argument("--block-size", type=int, default=32, help="block size in KB")
    p.add_argument(
        "--steps", type=int, default=32,
        help="simulate N layers: the batch is split into N sequential batched ops",
    )
    p.add_argument("--type", choices=["rdma", "tcp"], default="rdma",
                   help="rdma = batched zero-copy data plane; tcp = single-key ops")
    p.add_argument("--iteration", type=int, default=1)
    p.add_argument("--verify", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--json", action="store_true", help="emit one JSON line")
    p.add_argument(
        "--latency", action="store_true",
        help="also measure single-block fetch latency p50/p99 at 4KB and 64KB "
             "(the BASELINE.md 'p50 block-fetch latency' configs)",
    )
    p.add_argument(
        "--streams", type=int, default=1,
        help="connection stripes for batched ops (cross-host DCN scaling; "
             "see docs/multistream.md)",
    )
    p.add_argument(
        "--adaptive", action=argparse.BooleanOptionalAction, default=True,
        help="striped fan-out mode: adaptive work-stealing chunk scheduler "
             "(default) vs the legacy static 1/N split (--no-adaptive, for "
             "A/B comparison of the two data planes)",
    )
    p.add_argument(
        "--wave", type=int, default=0,
        help="also measure admission-wave read coalescing (N concurrent "
             "requests' reads issued as N separate calls vs merged into one "
             "— the FetchCoalescer mechanism; connector.py) AND the "
             "decode-wave cost: one ragged attention launch for an N-request "
             "length-skewed wave vs the padded-dense rectangle "
             "(tpu/paged_attention.py; same estimator as bench.py's decode "
             "leg)",
    )
    p.add_argument(
        "--trace", default=None, metavar="FILE|PRESET",
        help="replay a loadgen trace (JSON file or preset name: skewed, "
             "uniform, outlier_flood) through the continuous-batching "
             "engine harness against the server — the same traces "
             "bench.py's serving leg grades (docs/serving_load.md)",
    )
    p.add_argument(
        "--trace-seed", type=int, default=0,
        help="generator seed when --trace names a preset",
    )
    p.add_argument(
        "--trace-duration", type=float, default=0.4,
        help="trace duration in seconds when --trace names a preset",
    )
    p.add_argument(
        "--skew-policy", action=argparse.BooleanOptionalAction, default=True,
        help="replay with the skew-aware wave flush policy "
             "(wave_skew_policy; docs/serving_load.md) on or off",
    )
    p.add_argument(
        "--pacing-mbps", type=int, default=0,
        help="cap each connection's egress in MB/s (SO_MAX_PACING_RATE); "
             "implies the socket path (shm off — a same-host memcpy would "
             "bypass the cap). Emulates a bandwidth-limited cross-host "
             "stream; see tools/striping_emulation.py",
    )
    return p.parse_args(argv)


def _measure_latency(conn, samples: int = 200) -> dict:
    """p50/p99 single-block fetch latency at 4KB and 64KB.

    Sync (read_cache, the low-latency API: the calling thread blocks on the
    native completion) and async samples are taken in short INTERLEAVED
    chunks — hosts swing between seconds, and the async-minus-sync delta
    (``async_overhead_us``) only means 'bridge cost' when both paths saw
    the same weather (same discipline as bench.py's _fetch_latency_us)."""
    out = {}
    chunk = 50
    for size in (4 << 10, 64 << 10):
        buf = np.random.randint(0, 256, size=size, dtype=np.uint8)
        dst = np.zeros_like(buf)
        conn.register_mr(buf)
        conn.register_mr(dst)
        key = f"lat-{uuid.uuid4().hex[:8]}"

        async def async_chunk(k):
            lats = []
            for _ in range(k):
                t0 = time.perf_counter()
                await conn.read_cache_async([(key, 0)], size, dst.ctypes.data)
                lats.append((time.perf_counter() - t0) * 1e6)
            return lats

        async def seed():
            await conn.write_cache_async([(key, 0)], size, buf.ctypes.data)
            await conn.read_cache_async([(key, 0)], size, dst.ctypes.data)

        asyncio.run(seed())  # write + warm the async path
        conn.read_cache([(key, 0)], size, dst.ctypes.data)  # warm sync
        lats = []
        sync_lats = []
        for _ in range(max(1, samples // chunk)):
            for _ in range(chunk):
                t0 = time.perf_counter()
                conn.read_cache([(key, 0)], size, dst.ctypes.data)
                sync_lats.append((time.perf_counter() - t0) * 1e6)
            lats += asyncio.run(async_chunk(chunk))
        lats.sort()
        sync_lats.sort()
        p50 = lats[len(lats) // 2]
        sync_p50 = sync_lats[len(sync_lats) // 2]
        out[f"fetch_{size >> 10}kb"] = {
            "p50_us": round(p50, 1),
            "p99_us": round(lats[int(len(lats) * 0.99)], 1),
            "sync_p50_us": round(sync_p50, 1),
            "sync_p99_us": round(sync_lats[int(len(sync_lats) * 0.99)], 1),
            # The asyncio bridge's whole per-op tax in one number; its floor
            # is the eventfd loop wake (bench.py asyncio_efd_floor_us).
            "async_overhead_us": round(p50 - sync_p50, 1),
        }
        conn.delete_keys([key])
    return out


def _measure_wave_coalescing(conn, keys, offsets, block_size, dst, wave: int) -> dict:
    """N concurrent 'admissions' reading disjoint spans: N separate
    read_cache_async calls racing on the connection vs the SAME blocks
    merged into one call (what connector.FetchCoalescer does for a wave of
    engine admissions). The gain is per-call overhead amortization — the
    number striped deployments multiply, since one merged call splits
    across all stripes."""
    n = len(keys)
    # Exactly `wave` near-equal spans (never more, never fewer — except
    # when there are fewer keys than requests), so the reported
    # wave_requests is the concurrency actually raced.
    wave = min(wave, n)
    bounds = [round(j * n / wave) for j in range(wave + 1)]
    spans = [
        list(zip(keys[a:b], offsets[a:b]))
        for a, b in zip(bounds, bounds[1:])
        if b > a
    ]

    async def split():
        await asyncio.gather(*(
            conn.read_cache_async(span, block_size, dst.ctypes.data)
            for span in spans
        ))

    async def merged():
        await conn.read_cache_async(
            [b for span in spans for b in span], block_size, dst.ctypes.data
        )

    asyncio.run(split())  # warm
    best_split = best_merged = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        asyncio.run(split())
        best_split = min(best_split, time.perf_counter() - t0)
        t0 = time.perf_counter()
        asyncio.run(merged())
        best_merged = min(best_merged, time.perf_counter() - t0)
    moved_mb = n * block_size / (1 << 20)
    return {
        "wave_requests": len(spans),
        "wave_split_mb_s": round(moved_mb / best_split, 2),
        "wave_merged_mb_s": round(moved_mb / best_merged, 2),
        "wave_coalescing_gain": round(best_split / best_merged, 3),
    }


def _measure_decode_wave(wave: int) -> dict:
    """Decode-wave cost on the CONSUME side of the store: one ragged
    attention launch for a ``wave``-request, 8:1 length-skewed wave vs the
    padded-dense rectangle the engine's WaveDecoder used to assemble
    (every row padded to the wave max). Uses the same paged shapes and the
    same order-alternating paired interleaved sampling with the
    min(median-of-ratios, ratio-of-sums) estimator as ``bench.py``'s
    decode-attention leg, so this CLI harness and the bench agree on what
    a wave costs. Off-TPU both paths lower to the same XLA gather (the
    ragged fallback reconstructs rectangular tables), so the gain reads
    ~1.0 there by construction; the ragged win is a TPU-kernel property.
    Returns {} when jax is unavailable."""
    try:
        import jax.numpy as jnp

        from .tpu.paged_attention import (
            build_ragged_wave,
            paged_decode_attention_batched,
            paged_decode_attention_ragged,
        )
    except ImportError:
        return {}

    n, bt, kvh, d, h, ntbl = 256, 16, 2, 64, 8, 16
    wave = max(2, wave)
    rng = np.random.default_rng(0)
    k_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((n, bt, kvh, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((wave, h, d)), jnp.float32)
    lens = [ntbl * bt] + [ntbl * bt // 8] * (wave - 1)
    tables = [np.asarray(rng.permutation(n)[:ntbl]) for _ in range(wave)]
    meta = build_ragged_wave(tables, lens, bt, pad_to_pow2=True)
    tbls = jnp.asarray(np.stack(tables), jnp.int32)
    sls = jnp.asarray(meta.seq_lens)
    pages = jnp.asarray(meta.pages)
    rows = jnp.asarray(meta.page_rows)
    starts = jnp.asarray(meta.page_starts)

    def ragged(qc):
        return paged_decode_attention_ragged(
            qc, k_cache, v_cache, pages, rows, starts, sls, table_width=ntbl
        )

    def padded(qc):
        return paged_decode_attention_batched(qc, k_cache, v_cache, tbls, sls)

    reps = 8
    ragged(q).block_until_ready()  # compile + warm
    padded(q).block_until_ready()

    def sample(op) -> float:
        qc = q
        t0 = time.perf_counter()
        for _ in range(reps):
            qc = op(qc)
        qc.block_until_ready()
        return time.perf_counter() - t0

    sums = {"ragged": 0.0, "padded": 0.0}
    ratios = []
    for i in range(6):
        order = (
            ("ragged", "padded") if i % 2 else ("padded", "ragged")
        )
        s = {}
        for side in order:
            s[side] = sample(ragged if side == "ragged" else padded)
        for side in s:
            sums[side] += s[side]
        ratios.append(s["padded"] / s["ragged"])
    med = sorted(ratios)[len(ratios) // 2]
    gain = min(med, sums["padded"] / sums["ragged"])
    pairs = len(ratios)
    return {
        "wave_decode_requests": wave,
        "wave_decode_skew_factor": round(
            wave * max(lens) / sum(lens), 2
        ),
        "wave_decode_ragged_us": round(sums["ragged"] / (pairs * reps) * 1e6, 1),
        "wave_decode_padded_us": round(sums["padded"] / (pairs * reps) * 1e6, 1),
        "wave_decode_ragged_gain": round(gain, 3),
    }


def _run_trace(args) -> dict:
    """``--trace`` mode: replay a loadgen trace (file or preset) through
    the continuous-batching engine harness against the server — the same
    workload definition ``bench.py``'s ``_serving_trace_metrics`` leg
    grades, through the CLI entry point (docs/serving_load.md). Reports
    the harness's serving metrics (TTFT percentiles, wave pad fraction,
    the wave-policy ledger) plus the trace's own shape."""
    import os

    try:
        import jax
        import jax.numpy as jnp
    except ImportError as e:
        raise SystemExit(f"--trace needs jax for the engine harness: {e}")

    from . import loadgen
    from .connector import KVConnector
    from .engine import (
        ContinuousBatchingHarness,
        EngineKVAdapter,
        NGramDrafter,
    )
    from .models import LlamaConfig, init_params

    if os.path.exists(args.trace):
        trace = loadgen.Trace.load(args.trace)
    else:
        trace = loadgen.preset(
            args.trace, seed=args.trace_seed,
            duration_s=args.trace_duration,
        )

    cfg = LlamaConfig(
        vocab=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, block_tokens=8, dtype=jnp.float32,
    )
    num_blocks, max_blocks = 96, 8
    conn = InfinityConnection(ClientConfig(
        host_addr=args.host, service_port=args.service_port,
        log_level="warning",
    ))
    conn.connect()
    try:
        kvc = KVConnector(
            conn, cfg.kv_spec(num_blocks),
            f"trace-{uuid.uuid4().hex[:8]}", max_blocks=max_blocks,
        )
        h = ContinuousBatchingHarness(
            EngineKVAdapter(kvc),
            init_params(cfg, jax.random.PRNGKey(0)),
            cfg, num_blocks, max_blocks, verify=args.verify,
            wave_skew_policy=args.skew_policy,
        )
        h.drafter = NGramDrafter(max_draft=4)
        t0 = time.perf_counter()
        stats = asyncio.run(loadgen.replay(trace, h, concurrency=8))
        wall = time.perf_counter() - t0
        errs = [s for s in stats if isinstance(s, Exception)]
        if errs:
            raise SystemExit(f"trace replay failed: {errs[:3]}")
        m = h.metrics()
        return {
            "trace": args.trace,
            "trace_seed": trace.seed,
            "trace_requests": len(trace.requests),
            "trace_prefill_only": sum(
                1 for r in trace.requests if r.gen_tokens == 0
            ),
            "trace_background": sum(
                1 for r in trace.requests if r.priority != 0
            ),
            "skew_policy": bool(args.skew_policy),
            "replay_wall_s": round(wall, 3),
            "requests_per_s": round(len(trace.requests) / wall, 1),
            "verified": bool(m["all_verified"]) if args.verify else None,
            "hit_rate": round(m["hit_rate"], 3),
            "p50_ttft_us": m["p50_ttft_us"],
            "p99_ttft_us": m["p99_ttft_us"],
            "p99_ttft_fg_us": m["p99_ttft_fg_us"],
            "wave_pad_fraction": round(m["wave_pad_fraction"], 4),
            "decode_waves": m["decode_waves"],
            "wave_deferrals": m["wave_deferrals"],
            "wave_aging_escapes": m["wave_aging_escapes"],
            "wave_held_flushes": m["wave_held_flushes"],
            "wave_defer_age_us_p99": m["wave_defer_age_us_p99"],
        }
    finally:
        conn.close()


async def _run_batched(conn, keys, offsets, block_size, src, dst, steps):
    """Layer-wise streaming shape (reference benchmark.py:188-256): the block
    list is split into `steps` chunks issued as pipelined batched ops."""
    n = len(keys)
    per = max(1, n // steps)
    t0 = time.perf_counter()
    writes = []
    for s in range(0, n, per):
        blocks = list(zip(keys[s : s + per], offsets[s : s + per]))
        writes.append(conn.write_cache_async(blocks, block_size, src.ctypes.data))
    await asyncio.gather(*writes)
    t1 = time.perf_counter()
    reads = []
    for s in range(0, n, per):
        blocks = list(zip(keys[s : s + per], offsets[s : s + per]))
        reads.append(conn.read_cache_async(blocks, block_size, dst.ctypes.data))
    await asyncio.gather(*reads)
    t2 = time.perf_counter()
    return t1 - t0, t2 - t1


def run(args) -> dict:
    cfg = ClientConfig(
        host_addr=args.host,
        service_port=args.service_port,
        connection_type=TYPE_RDMA if args.type == "rdma" else TYPE_TCP,
        log_level="warning",
        pacing_rate_mbps=args.pacing_mbps,
        # Pacing shapes SOCKET egress; the same-host shm fast path moves
        # payloads by memcpy and would silently bypass the cap.
        enable_shm=args.pacing_mbps == 0,
    )
    if args.streams > 1:
        conn = StripedConnection(cfg, streams=args.streams, adaptive=args.adaptive)
    else:
        conn = InfinityConnection(cfg)
    conn.connect()

    total_bytes = args.size << 20
    block_size = args.block_size << 10
    nblocks = max(1, total_bytes // block_size)
    total_bytes = nblocks * block_size

    src = np.random.randint(0, 256, size=total_bytes, dtype=np.uint8)
    dst = np.zeros_like(src)
    run_id = uuid.uuid4().hex[:8]
    keys = [f"bench-{run_id}-{i}" for i in range(nblocks)]
    offsets = [i * block_size for i in range(nblocks)]

    write_s = read_s = 0.0
    try:
        if args.type == "rdma":
            conn.register_mr(src)
            conn.register_mr(dst)
            for _ in range(args.iteration):
                w, r = asyncio.run(
                    _run_batched(conn, keys, offsets, block_size, src, dst, args.steps)
                )
                write_s += w
                read_s += r
        else:
            for _ in range(args.iteration):
                t0 = time.perf_counter()
                for i, key in enumerate(keys):
                    conn.tcp_write_cache(
                        key, src.ctypes.data + offsets[i], block_size
                    )
                t1 = time.perf_counter()
                for i, key in enumerate(keys):
                    out = conn.tcp_read_cache(key)
                    dst[offsets[i] : offsets[i] + block_size] = out
                t2 = time.perf_counter()
                write_s += t1 - t0
                read_s += t2 - t1

        ok = bool(np.array_equal(src, dst)) if args.verify else None
        moved = total_bytes * args.iteration
        result = {
            "type": args.type,
            "blocks": nblocks,
            "block_size_kb": args.block_size,
            "total_mb": moved >> 20,
            "write_mb_s": round(moved / write_s / (1 << 20), 2),
            "read_mb_s": round(moved / read_s / (1 << 20), 2),
            "verified": ok,
        }
        if args.latency and args.type == "rdma":
            result["latency"] = _measure_latency(conn)
        if args.wave > 1 and args.type == "rdma":
            result["coalescing"] = _measure_wave_coalescing(
                conn, keys, offsets, block_size, dst, args.wave
            )
        if args.wave > 1:
            # The consume-side half of the wave story: what the DECODE
            # launch for this wave costs through the ragged path vs the
            # padded rectangle (same estimator as bench.py's decode leg).
            decode = _measure_decode_wave(args.wave)
            if decode:
                result["decode_wave"] = decode
        if args.type == "rdma":
            # Wakeup coalescing over the whole run (native ring pushes vs
            # eventfd signals; >1 means pipelined ops shared loop wakes).
            result["completion_batch_size"] = round(
                conn.completion_stats()["completion_batch_size"], 2
            )
        if args.streams > 1:
            # Adaptive scheduler receipt: per-stripe chunk/block counts,
            # steals, EWMA rates, and same-host collapse count.
            result["striping"] = conn.data_plane_stats()
        conn.delete_keys(keys)
        return result
    finally:
        conn.close()


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.trace:
        result = _run_trace(args)
        if args.json:
            print(json.dumps(result))
        else:
            print(
                f"replayed {result['trace_requests']} requests "
                f"({result['trace']}) in {result['replay_wall_s']}s "
                f"(skew_policy={'on' if result['skew_policy'] else 'off'})"
            )
            print(
                f"p99 TTFT: {result['p99_ttft_us']}us (fg "
                f"{result['p99_ttft_fg_us']}us), pad fraction "
                f"{result['wave_pad_fraction']}, deferrals "
                f"{result['wave_deferrals']}"
            )
            if result["verified"] is not None:
                print(f"data verified: {result['verified']}")
        return 0 if result.get("verified") in (True, None) else 1
    result = run(args)
    if args.json:
        print(json.dumps(result))
    else:
        print(f"write throughput: {result['write_mb_s']} MB/s")
        print(f"read throughput: {result['read_mb_s']} MB/s")
        if result["verified"] is not None:
            print(f"data verified: {result['verified']}")
    return 0 if result.get("verified") in (True, None) else 1


if __name__ == "__main__":
    raise SystemExit(main())
