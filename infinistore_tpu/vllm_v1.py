"""vLLM v1 ``KVConnectorBase_V1`` implementation over the InfiniStore-TPU store.

The reference's entire reason to exist is serving vLLM through LMCache
(reference README.md:22, docs/source/design.rst:33-37). vLLM v1 made that
seam a first-class plugin: ``KVConnectorBase_V1``
(vllm/distributed/kv_transfer/kv_connector/v1/base.py) with a scheduler-side
half that decides WHAT to transfer and a worker-side half that moves bytes
during the forward pass, connected by an opaque metadata object the scheduler
builds each step and the runner binds before the model runs. This module
implements that published contract — same method names, signatures, call
order, and role split — so attaching this store to a vLLM-TPU engine is
``--kv-connector InfiniStoreKVConnectorV1`` configuration, not engine code.

Published call order (the contract tests in tests/test_vllm_v1.py drive
exactly this):

  scheduler, per request:  get_num_new_matched_tokens -> (engine allocates)
                           -> update_state_after_alloc
  scheduler, per step:     build_connector_meta  (ships to the worker)
  worker, per step:        bind_connector_metadata -> start_load_kv
                           -> [per layer: wait_for_layer_load BEFORE the
                               layer's attention reads the cache;
                               save_kv_layer AFTER the layer's KV insert]
                           -> wait_for_save -> clear_connector_metadata
  scheduler, at finish:    request_finished

Two deliberate TPU-native adaptations, both documented on the methods:

- **Functional caches.** vLLM's torch connectors mutate the worker's paged
  KV tensors in place; jax arrays are immutable and our scatters DONATE
  their inputs (tpu/paged.py). The worker half therefore owns the
  authoritative per-layer cache references between ``register_kv_caches``
  and the end of the step: loads swap refs layer by layer, and the engine
  reads the current arrays with ``kv_cache(layer_name)`` after each
  ``wait_for_layer_load`` — the functional spelling of "the tensor the
  engine handed us got filled".

- **Sentinel-honoring layer-wise save.** ``save_kv_layer`` streams each
  layer out as its forward completes (the reference's layer-wise overlap,
  design.rst:54-63) — except layer 0, whose store keys are the
  whole-block presence sentinel (connector.py lookup): its bytes are
  staged immediately but its PUT is deferred to ``wait_for_save``, after
  every deeper layer committed. A concurrent lookup therefore never sees
  a half-saved block as a hit.

The scheduler and worker halves are separate instances (vLLM runs them in
separate processes); each builds its own store connection. Loads run on a
private background event loop owned by the worker half — the store's
asyncio ops bind to the loop that awaits them (lib.py), and vLLM's runner
calls are synchronous.
"""

import asyncio
import enum
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import wire
from .connector import KVConnector  # noqa: F401 - the canonical surface
from .tpu.staging import StagingPoolExhausted


class KVLoadUnderDelivery(RuntimeError):
    """A load delivered fewer tokens than the scheduler was promised.

    Stock vLLM counts the promised tokens as computed the moment the
    scheduler builds the step, so silently under-delivering (a store-side
    eviction racing the load) would make the engine attend over zero-filled
    blocks and emit wrong output. Raised from ``wait_for_layer_load`` /
    ``wait_for_save`` unless the engine opts into the ``loaded_tokens()``
    recompute protocol (``allow_partial_delivery`` in the connector's extra
    config)."""


class KVConnectorRole(enum.Enum):
    """Which half of the engine this instance serves (published enum)."""

    SCHEDULER = 0
    WORKER = 1


class KVConnectorMetadata:
    """Opaque scheduler->worker payload (published base: an empty marker
    class; concrete connectors subclass it)."""


@dataclass
class _LoadSpec:
    """One request's prefix load, decided scheduler-side."""

    req_id: str
    token_ids: List[int]
    block_ids: np.ndarray  # engine physical blocks for the loaded span
    num_tokens: int  # external tokens to load (block-aligned)
    first_block: int  # logical block where the external span starts


@dataclass
class _SaveSpec:
    """One request's computed-suffix save."""

    req_id: str
    token_ids: List[int]
    block_ids: np.ndarray  # physical blocks holding the computed suffix
    first_block: int  # logical index of block_ids[0] within the prompt


@dataclass
class InfiniStoreConnectorMetadata(KVConnectorMetadata):
    """Per-step transfer plan: built by the scheduler half, consumed by the
    worker half."""

    loads: List[_LoadSpec] = field(default_factory=list)
    saves: List[_SaveSpec] = field(default_factory=list)


class KVConnectorBase_V1(ABC):
    """The published vLLM v1 connector contract, mirrored method-for-method
    (vllm/distributed/kv_transfer/kv_connector/v1/base.py). vLLM is not a
    dependency of this package, so the ABC is restated here; the signatures
    and the scheduler/worker role split are the published ones — a vLLM tree
    can subclass its own base instead and reuse ``InfiniStoreKVConnectorV1``
    unchanged."""

    def __init__(self, vllm_config, role: "KVConnectorRole"):
        self._connector_metadata: Optional[KVConnectorMetadata] = None
        self.role = role

    # -- worker-side ---------------------------------------------------------

    def bind_connector_metadata(self, connector_metadata: KVConnectorMetadata):
        """Runner installs this step's metadata before the forward pass."""
        self._connector_metadata = connector_metadata
        self._reset_step_state()

    def _reset_step_state(self):
        """Hook for subclasses with per-step worker state (overridden by
        the concrete connector; the base has none)."""

    def clear_connector_metadata(self):
        """Runner clears it after the step."""
        self._connector_metadata = None

    @abstractmethod
    def start_load_kv(self, forward_context, **kwargs) -> None:
        """Begin loading external KV for the bound metadata's requests."""

    @abstractmethod
    def wait_for_layer_load(self, layer_name: str) -> None:
        """Block until ``layer_name``'s load landed (called before that
        layer's attention)."""

    @abstractmethod
    def save_kv_layer(self, layer_name: str, kv_layer, attn_metadata, **kwargs) -> None:
        """Start saving ``layer_name`` (called after that layer's forward)."""

    @abstractmethod
    def wait_for_save(self) -> None:
        """Block until every save issued this step is durable."""

    def get_finished(self, finished_req_ids) -> Tuple[Optional[set], Optional[set]]:
        """(sending-finished, recving-finished) request ids for ASYNC
        transfer connectors. Ours completes synchronously within the step
        (wait_for_save / wait_for_layer_load), so there is never a deferred
        set: (None, None) — the published 'nothing outstanding' answer."""
        return None, None

    # -- scheduler-side ------------------------------------------------------

    @abstractmethod
    def get_num_new_matched_tokens(
        self, request, num_computed_tokens: int
    ) -> Tuple[int, bool]:
        """(tokens available externally BEYOND num_computed_tokens,
        load_is_async)."""

    @abstractmethod
    def update_state_after_alloc(self, request, blocks, num_external_tokens: int):
        """Engine allocated blocks for the promised external tokens."""

    @abstractmethod
    def build_connector_meta(self, scheduler_output) -> KVConnectorMetadata:
        """Assemble this step's metadata and RESET per-step scheduler state."""

    def request_finished(self, request, block_ids) -> Tuple[bool, Optional[dict]]:
        """Request left the engine. Returns (delay_block_free, transfer
        params for the response). Saves here are synchronous within the
        step, so blocks never need delayed freeing."""
        return False, None


def _iter_cached_reqs(cached):
    """Yield (req_id, new_block_ids, num_computed_tokens, resumed) from
    vLLM's ``scheduled_cached_reqs``, duck-typing both published shapes: a
    list of CachedRequestData objects, or the newer struct-of-arrays object
    with parallel ``req_ids`` / ``new_block_ids`` / ``num_computed_tokens``
    / ``resumed_from_preemption``."""
    if cached is None:
        return
    req_ids = getattr(cached, "req_ids", None)
    if req_ids is not None:
        n = len(req_ids)
        new_blocks = getattr(cached, "new_block_ids", None) or [None] * n
        computed = getattr(cached, "num_computed_tokens", None) or [0] * n
        resumed = (
            getattr(cached, "resumed_from_preemption", None) or [False] * n
        )
        yield from zip(req_ids, new_blocks, computed, resumed)
        return
    for r in cached:
        yield (
            r.req_id,
            getattr(r, "new_block_ids", None),
            getattr(r, "num_computed_tokens", 0),
            getattr(r, "resumed_from_preemption", False),
        )


def _block_ids_of(blocks) -> np.ndarray:
    """Accept vLLM's KVCacheBlocks (``get_block_ids()`` -> [[ids]]), its
    per-group nested lists ([[ids]], one entry per KV cache group — we
    serve group 0, the standard full-attention group), or a plain id
    sequence."""
    if hasattr(blocks, "get_block_ids"):
        return np.asarray(blocks.get_block_ids()[0], dtype=np.int32)
    seq = list(blocks)
    if seq and isinstance(seq[0], (list, tuple, np.ndarray)):
        seq = list(seq[0])
    return np.asarray(seq, dtype=np.int32)


class InfiniStoreKVConnectorV1(KVConnectorBase_V1):
    """The store's vLLM v1 connector.

    ``vllm_config`` duck-types vLLM's config object: the connector reads
    ``vllm_config.kv_transfer_config.kv_connector_extra_config`` (falling
    back to ``vllm_config`` itself being that dict) and expects one key,
    ``"kv_connector"``: a built :class:`~infinistore_tpu.connector.KVConnector`
    binding the model's cache spec to a store connection. Each role builds
    its own (scheduler and worker live in different processes in vLLM).
    """

    def __init__(self, vllm_config, role: KVConnectorRole):
        super().__init__(vllm_config, role)
        extra = vllm_config
        ktc = getattr(vllm_config, "kv_transfer_config", None)
        if ktc is not None:
            extra = getattr(ktc, "kv_connector_extra_config", ktc)
        if isinstance(extra, dict):
            kv = extra.get("kv_connector")
        else:
            kv = getattr(extra, "kv_connector", None)
        # Duck-typed, not isinstance: ClusterKVConnector (cluster.py) and
        # any KVConnector-shaped member expose the same surface, so a
        # pooled store drops in here with no engine-side change.
        needed = ("spec", "lookup", "load", "stage_layer_save")
        missing = [a for a in needed if not hasattr(kv, a)]
        if missing:
            raise ValueError(
                "kv_connector_extra_config['kv_connector'] must expose the "
                f"KVConnector surface ({', '.join(needed)}); "
                f"{type(kv).__name__} lacks {', '.join(missing)}"
            )
        self.kv = kv
        self.block_tokens = kv.spec.block_tokens
        # Opt-in to graceful under-delivery: the engine promises to call
        # loaded_tokens() and recompute the shortfall. Without it, a load
        # delivering less than promised fails the step loudly
        # (KVLoadUnderDelivery) — stock vLLM would otherwise attend over
        # zero-filled blocks.
        if isinstance(extra, dict):
            self._allow_partial = bool(extra.get("allow_partial_delivery", False))
        else:
            self._allow_partial = bool(
                getattr(extra, "allow_partial_delivery", False)
            )
        # scheduler-side per-step state
        self._pending_loads: Dict[str, _LoadSpec] = {}
        self._probed_tokens: Dict[str, int] = {}  # req -> engine-computed blocks
        self._store_hits: Dict[str, int] = {}  # req -> store's hit blocks
        # scheduler-side per-REQUEST state (persists across steps; cleared
        # in request_finished): chunked prefill's later chunks arrive via
        # scheduled_cached_reqs carrying no prompt tokens, so the first
        # step's data and a saved-block watermark must be remembered or the
        # tail of a long prompt never reaches the store.
        self._save_watermark: Dict[str, int] = {}  # req -> blocks saved/stored
        self._req_tokens: Dict[str, List[int]] = {}
        self._req_blocks: Dict[str, List[int]] = {}
        # worker-side state
        self._layer_names: List[str] = []
        self._layer_index: Dict[str, int] = {}
        self._kv_caches: List[Tuple[jax.Array, jax.Array]] = []
        self._kv_lock = threading.Lock()
        self._load_done: List[threading.Event] = []
        self._load_error: Optional[BaseException] = None
        self._loaded_tokens: Dict[str, int] = {}
        self._save_futures: list = []
        self._deferred_sentinels: list = []
        self._load_future = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None

    # ======================================================================
    # scheduler side
    # ======================================================================

    def get_num_new_matched_tokens(self, request, num_computed_tokens: int):
        """Probe the store for this prompt's longest cached prefix (one
        control round trip — connector.py lookup) and promise the tokens
        the engine does NOT already have locally. Block-aligned both ways:
        ``num_computed_tokens`` is floored to the block grid before
        subtracting, so a partially-computed block never double-counts.
        The promise is capped so AT LEAST ONE prompt token remains for the
        engine to compute — vLLM's scheduler requires a non-empty local
        step per request (the same cap every published connector applies);
        the cap drops whole blocks, keeping loads block-granular.
        Returns (count, False): loads complete inside the step via
        wait_for_layer_load, never asynchronously across steps."""
        hit_blocks = self.kv.lookup(request.prompt_token_ids)
        computed_blocks = num_computed_tokens // self.block_tokens
        external = max(0, (hit_blocks - computed_blocks) * self.block_tokens)
        cap = len(request.prompt_token_ids) - num_computed_tokens - 1
        if external > cap:
            external = max(0, (cap // self.block_tokens) * self.block_tokens)
        self._probed_tokens[request.request_id] = computed_blocks
        self._store_hits[request.request_id] = hit_blocks
        return external, False

    def update_state_after_alloc(self, request, blocks, num_external_tokens: int):
        """Record the engine's physical placement for the promised tokens.
        ``blocks`` covers the whole request; the external span occupies the
        entries just after the engine's locally-computed prefix, so the
        load targets ``blocks[computed : computed + external]`` and fetches
        exactly the chain span it promised (KVConnector.load first_block)."""
        if num_external_tokens <= 0:
            return
        ids = _block_ids_of(blocks)
        skip = self._probed_tokens.get(request.request_id, 0)
        n_blocks = num_external_tokens // self.block_tokens
        self._pending_loads[request.request_id] = _LoadSpec(
            req_id=request.request_id,
            token_ids=list(request.prompt_token_ids),
            block_ids=ids[skip : skip + n_blocks],
            num_tokens=n_blocks * self.block_tokens,
            first_block=skip,
        )

    def build_connector_meta(self, scheduler_output) -> InfiniStoreConnectorMetadata:
        """Assemble this step's plan: the loads recorded since the last
        build, plus a save of every scheduled request's computed suffix
        (the loaded prefix is already stored — re-saving it would double
        write traffic on every hit). PER-STEP scheduler state resets here;
        per-REQUEST state (the saved-block watermark) persists across
        steps so a chunked prefill's later chunks — which arrive via
        ``scheduled_cached_reqs`` with no prompt data — still emit their
        saves, and is cleared in ``request_finished``."""
        meta = InfiniStoreConnectorMetadata(loads=list(self._pending_loads.values()))
        # Chunked prefill: scheduler_output.num_scheduled_tokens (vLLM's
        # per-request dict) bounds what this step actually computes; only
        # blocks COMPLETE by end of step may be saved — committing an
        # unscheduled block would publish garbage under a valid chain key.
        # Absent the attribute, the whole prompt runs this step.
        num_sched = getattr(scheduler_output, "num_scheduled_tokens", None) or {}
        for req in getattr(scheduler_output, "scheduled_new_reqs", []):
            rid = req.req_id
            ids = _block_ids_of(req.block_ids)
            end_tokens = len(req.prompt_token_ids)
            if rid in num_sched:
                end_tokens = min(
                    end_tokens, req.num_computed_tokens + num_sched[rid]
                )
            end_blocks = end_tokens // self.block_tokens
            # Everything the store already holds — the probed hit prefix —
            # is skipped; blocks the engine computed LOCALLY beyond the
            # store's hit (its own prefix cache outran the store) are saved
            # too, or the store could never learn them.
            hit = self._store_hits.get(rid, 0)
            start = max(min(hit, end_blocks), self._save_watermark.get(rid, 0))
            if end_blocks > start:
                meta.saves.append(
                    _SaveSpec(
                        req_id=rid,
                        token_ids=list(req.prompt_token_ids),
                        block_ids=ids[start:end_blocks],
                        first_block=start,
                    )
                )
            # Remember what a resumed (cached) step will need, and advance
            # the watermark past everything now saved OR already in store.
            self._req_tokens[rid] = list(req.prompt_token_ids)
            self._req_blocks[rid] = [int(i) for i in ids]
            self._save_watermark[rid] = max(hit, end_blocks)
        for rid, new_ids, num_computed, resumed in _iter_cached_reqs(
            getattr(scheduler_output, "scheduled_cached_reqs", None)
        ):
            tokens = self._req_tokens.get(rid)
            if tokens is None:
                continue  # not a request we admitted (or already finished)
            if resumed:
                # Preemption freed (and likely re-used) every old physical
                # block; new_block_ids is the FULL replacement list, not an
                # extension — appending would misalign logical->physical
                # and gather other requests' data under this prompt's
                # chain keys. The watermark survives: already-saved blocks
                # are content-addressed by tokens and stay valid.
                self._req_blocks[rid] = []
            blocks = self._req_blocks.setdefault(rid, [])
            if new_ids is not None:
                ext = _block_ids_of(new_ids)
                if len(ext):
                    blocks.extend(int(i) for i in ext)
            end_tokens = len(tokens)
            if rid in num_sched:
                end_tokens = min(end_tokens, int(num_computed) + num_sched[rid])
            end_blocks = min(end_tokens // self.block_tokens, len(blocks))
            start = self._save_watermark.get(rid, 0)
            if end_blocks > start:
                meta.saves.append(
                    _SaveSpec(
                        req_id=rid,
                        token_ids=list(tokens),
                        block_ids=np.asarray(blocks[start:end_blocks], np.int32),
                        first_block=start,
                    )
                )
                self._save_watermark[rid] = end_blocks
        self._pending_loads.clear()
        self._probed_tokens.clear()
        self._store_hits.clear()
        return meta

    def request_finished(self, request, block_ids) -> Tuple[bool, Optional[dict]]:
        """Request left the engine: drop its cross-step tracking (saved-
        block watermark, remembered prompt/blocks). Saves are synchronous
        within the step, so blocks never need delayed freeing."""
        rid = getattr(request, "request_id", None) or getattr(
            request, "req_id", None
        )
        if rid is not None:
            self._save_watermark.pop(rid, None)
            self._req_tokens.pop(rid, None)
            self._req_blocks.pop(rid, None)
        return False, None

    # ======================================================================
    # worker side
    # ======================================================================

    def register_kv_caches(self, kv_caches: Dict[str, Tuple[jax.Array, jax.Array]]):
        """Install the engine's paged caches, one (K, V) pair per layer, in
        FORWARD ORDER (dict order = layer order, as vLLM's runner builds
        it). The connector holds the authoritative refs from here on —
        jax's functional updates mean loads produce NEW arrays; read the
        current ones back with ``kv_cache``/``kv_caches``."""
        self._layer_names = list(kv_caches.keys())
        self._layer_index = {n: i for i, n in enumerate(self._layer_names)}
        self._kv_caches = [kv_caches[n] for n in self._layer_names]

    def kv_cache(self, layer_name: str) -> Tuple[jax.Array, jax.Array]:
        """Current (K, V) arrays for a layer — call after
        ``wait_for_layer_load`` to get the load's output (TPU-functional
        reading of vLLM's in-place tensor fill)."""
        with self._kv_lock:
            return self._kv_caches[self._layer_index[layer_name]]

    def kv_caches(self) -> List[Tuple[jax.Array, jax.Array]]:
        """Current per-layer cache list (forward order)."""
        with self._kv_lock:
            return list(self._kv_caches)

    def loaded_tokens(self, req_id: str) -> int:
        """Tokens actually delivered for a request this step (== the
        promise unless a store-side eviction raced the load; cache
        semantics — the engine recomputes the difference)."""
        return self._loaded_tokens.get(req_id, 0)

    def _reset_step_state(self):
        """A step aborted mid-forward (load error, engine preemption) must
        not leak its staged saves into the next step: a stale layer-0
        sentinel shipping later would publish presence for blocks whose
        deeper layers never committed — a poisoned prefix every consumer
        would hit. Dropping the sentinels keeps the aborted step invisible
        (deeper-layer puts that already landed are unreachable without the
        sentinel, and get overwritten on the retry)."""
        self._deferred_sentinels = []
        self._save_futures = []

    def _ensure_loop(self) -> asyncio.AbstractEventLoop:
        if self._loop is None:
            loop = asyncio.new_event_loop()

            def run():
                asyncio.set_event_loop(loop)
                loop.run_forever()

            t = threading.Thread(target=run, name="infinistore-v1-io", daemon=True)
            t.start()
            self._loop, self._loop_thread = loop, t
        return self._loop

    def start_load_kv(self, forward_context, **kwargs) -> None:
        """Kick off this step's loads on the background I/O loop.

        Requests load SEQUENTIALLY (each load donates and replaces the
        shared cache arrays — two concurrent loads would scatter into
        deleted buffers; the engine-harness DeviceGate exists for the same
        reason), but each request's layers pipeline internally
        (LayerwiseKVReader overlaps fetch/H2D/scatter). Per-layer progress
        feeds ``wait_for_layer_load``: layer L's event fires once EVERY
        request's layer L landed."""
        meta = self._connector_metadata
        if not isinstance(meta, InfiniStoreConnectorMetadata):
            raise RuntimeError(
                "start_load_kv before bind_connector_metadata (the runner "
                "must bind this step's metadata first)"
            )
        num_layers = len(self._kv_caches)
        if num_layers == 0:
            raise RuntimeError("register_kv_caches was never called")
        self._load_error = None
        self._loaded_tokens = {}
        self._load_future = None
        self._load_done = [threading.Event() for _ in range(num_layers)]
        loads = list(meta.loads)
        if not loads:
            for ev in self._load_done:
                ev.set()
            return
        remaining = [len(loads)] * num_layers

        async def run_loads():
            try:
                # Phase 1 — start every request's GATE-FREE fetch now
                # (KVConnector.start_fetch): the whole wave's store reads
                # run concurrently and coalesce into shared batched calls
                # (a StripedConnection splits them across its stripes),
                # instead of each request's network time queueing behind
                # the previous request's install. A full staging arena
                # just drops that request back to the one-phase load.
                fetch_async = getattr(self.kv, "start_fetch_async", None)
                can_fetch = fetch_async is not None or hasattr(
                    self.kv, "start_fetch"
                )
                handles = []
                for spec in loads:
                    handle = None
                    if can_fetch:
                        try:
                            if fetch_async is not None:
                                # Probe RTT in an executor — one request's
                                # lookup must not stall the wave (ITS-L001).
                                handle = await fetch_async(
                                    spec.token_ids,
                                    first_block=spec.first_block,
                                    limit_blocks=len(spec.block_ids),
                                )
                            else:
                                # Audited: sync-only duck-typed connector —
                                # the inline probe is its documented cost.
                                handle = self.kv.start_fetch(  # its: allow[ITS-L001]
                                    spec.token_ids,
                                    first_block=spec.first_block,
                                    limit_blocks=len(spec.block_ids),
                                )
                        except StagingPoolExhausted:
                            handle = None
                    handles.append(handle)
                # Phase 2 — install sequentially (each install donates and
                # replaces the shared cache arrays; two concurrent installs
                # would scatter into deleted buffers — the engine-harness
                # DeviceGate exists for the same reason), layer by layer.
                # Per-layer progress feeds ``wait_for_layer_load``: layer
                # L's event fires once EVERY request's layer L landed.
                for spec, handle in zip(loads, handles):
                    fired = set()

                    def on_layer(layer, kv, fired=fired):
                        fired.add(layer)
                        with self._kv_lock:
                            self._kv_caches[layer] = kv
                        remaining[layer] -= 1
                        if remaining[layer] == 0:
                            self._load_done[layer].set()

                    # Audited: microsecond list copy under an uncontended
                    # lock shared with the worker thread's layer waits.
                    with self._kv_lock:  # its: allow[ITS-L003]
                        caches = list(self._kv_caches)
                    if handle is not None:
                        _out, loaded = await handle.install(
                            caches,
                            spec.block_ids[: handle.n_blocks],
                            on_layer=on_layer,
                        )
                    else:
                        _out, loaded = await self.kv.load(
                            spec.token_ids,
                            caches,
                            spec.block_ids,
                            first_block=spec.first_block,
                            on_layer=on_layer,
                        )
                    self._loaded_tokens[spec.req_id] = loaded * self.block_tokens
                    # Settle layers on_layer never reached for THIS spec
                    # (no read at all, or a partial read that failed after
                    # some layers) — decrementing all layers again would
                    # release waits while a later spec's load is still
                    # scattering into the same arrays. A hook-less return
                    # may still have REPLACED a layer's arrays (donation:
                    # e.g. the quantized connector's scales-race degrade
                    # path donates every layer and returns 0) — install the
                    # returned refs, or _kv_caches keeps pointing at
                    # deleted TPU buffers for the rest of the step.
                    for layer in range(num_layers):
                        if layer not in fired:
                            if _out is not None and _out[layer] is not caches[layer]:
                                # Audited: single-item assignment, same lock
                                # discipline as above.
                                with self._kv_lock:  # its: allow[ITS-L003]
                                    self._kv_caches[layer] = tuple(_out[layer])
                            remaining[layer] -= 1
                            if remaining[layer] == 0:
                                self._load_done[layer].set()
                    if (
                        loaded * self.block_tokens < spec.num_tokens
                        and not self._allow_partial
                    ):
                        # The scheduler already counted the promise as
                        # computed; silently delivering less would make the
                        # engine attend over zero-filled blocks.
                        raise KVLoadUnderDelivery(
                            f"request {spec.req_id!r}: promised "
                            f"{spec.num_tokens} external tokens, delivered "
                            f"{loaded * self.block_tokens} (raced eviction?). "
                            "Opt into the loaded_tokens() recompute protocol "
                            "with allow_partial_delivery=True if the engine "
                            "recomputes shortfalls."
                        )
            except BaseException as e:  # noqa: BLE001 - surfaced by waits
                self._load_error = e
                # Unconsumed prefetches must hand their staging slots back.
                for h in handles:
                    if h is not None and h.blocks_installed == 0 and h.n_blocks:
                        try:
                            await h.discard()
                        except Exception:
                            pass
            finally:
                for ev in self._load_done:
                    ev.set()

        self._load_future = asyncio.run_coroutine_threadsafe(
            run_loads(), self._ensure_loop()
        )

    def wait_for_layer_load(self, layer_name: str) -> None:
        """Block until every bound load delivered ``layer_name``. The
        runner calls this immediately before the layer's attention; layers
        complete in forward order, so by construction the wait for layer L
        overlaps the network/H2D work of layers > L (the reference's
        layer-wise streaming contract, design.rst:54-63)."""
        self._load_done[self._layer_index[layer_name]].wait()
        if self._load_error is not None:
            raise RuntimeError(
                f"KV load failed before {layer_name!r}"
            ) from self._load_error

    def save_kv_layer(self, layer_name: str, kv_layer, attn_metadata, **kwargs) -> None:
        """Stream one layer's computed blocks to the store, overlapping the
        remaining layers' forward. ``kv_layer`` is the layer's (K, V) pair
        AFTER its KV insert (pass None to use the connector's current ref).
        Layer 0's bytes are gathered and staged NOW but its put is deferred
        to ``wait_for_save`` — layer-0 keys are the whole-block presence
        sentinel and must commit last (connector.py lookup)."""
        meta = self._connector_metadata
        if not isinstance(meta, InfiniStoreConnectorMetadata):
            raise RuntimeError("save_kv_layer before bind_connector_metadata")
        layer = self._layer_index[layer_name]
        if kv_layer is None:
            kv_layer = self.kv_cache(layer_name)
        with self._kv_lock:
            self._kv_caches[layer] = tuple(kv_layer)
        loop = self._ensure_loop()
        for spec in meta.saves:
            # Gather + D2H start here (runner thread) so later compute
            # cannot perturb the shipped bytes; the network put is a pure-
            # await callable (KVConnector.stage_layer_save — also the seam
            # where ClusterKVConnector routes by chain root).
            # BACKGROUND named at source (ITS-P004): this is the engine's
            # own streamed save behind the forward pass, NOT a handoff a
            # decode consumer is waiting on — disagg.py ships FOREGROUND.
            ship = self.kv.stage_layer_save(
                spec.token_ids, layer, kv_layer, spec.block_ids,
                first_block=spec.first_block,
                priority=wire.PRIORITY_BACKGROUND,
            )
            if layer == 0:
                self._deferred_sentinels.append(ship)
            else:
                self._save_futures.append(
                    asyncio.run_coroutine_threadsafe(ship(), loop)
                )

    def wait_for_save(self) -> None:
        """Drain every non-sentinel save, then ship the deferred layer-0
        sentinel puts and drain those — after this returns, every block
        saved this step is durably visible, and only then does its
        presence sentinel exist. Also joins the step's LOAD pipeline:
        per-layer waits return at each layer's scatter, so the end-of-step
        accounting (``loaded_tokens``) settles here."""
        if self._load_future is not None:
            self._load_future.result()
            self._load_future = None
        if self._load_error is not None:
            # A failed or under-delivered load must not slip past the step
            # boundary just because no later layer wait observed it.
            raise RuntimeError("KV load failed this step") from self._load_error
        try:
            for f in self._save_futures:
                f.result()
        finally:
            self._save_futures = []
        sentinels, self._deferred_sentinels = self._deferred_sentinels, []
        if sentinels:
            loop = self._ensure_loop()

            async def run_all():
                await asyncio.gather(*(p() for p in sentinels))

            asyncio.run_coroutine_threadsafe(run_all(), loop).result()

    def close(self):
        """Stop the background I/O loop (worker teardown)."""
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._loop_thread.join(timeout=5)
            self._loop = None
