"""Shared rate-shaped measurement helper.

One implementation of the shaped striped roundtrip, used by ``bench.py``,
``tools/striping_emulation.py``, and ``tests/test_striping_shaped.py`` — the
three must measure the same workload or the bench, tool, and CI test silently
diverge. The shaping itself is ``pacing_rate_mbps`` (SO_MAX_PACING_RATE, TCP
internal pacing): the client knob caps PUT egress, the server knob caps GET
egress, together emulating a bandwidth-limited cross-host stream on loopback.
"""

import asyncio
import time
from typing import Optional, Tuple

import numpy as np

from .config import ClientConfig
from .lib import InfinityConnection, StripedConnection

BLOCK = 64 << 10


def shaped_config(port: int, cap_mbps: int) -> ClientConfig:
    """Loopback client config with per-connection pacing and shm disabled
    (every byte rides the paced socket)."""
    return ClientConfig(
        host_addr="127.0.0.1",
        service_port=port,
        log_level="error",
        enable_shm=False,  # force the socket path: that is what stripes split
        pacing_rate_mbps=cap_mbps,
    )


def shaped_roundtrip_mbps(
    port: int,
    cap_mbps: int,
    streams: int,
    nbytes: int,
    key_prefix: str = "shaped",
    verify: bool = False,
) -> Tuple[float, Optional[bool]]:
    """Aggregate write+read MB/s of the headline workload over N paced
    stripes against the (server-side paced) store on ``port``.

    Returns (mbps, verified): ``verified`` is None unless ``verify`` — the
    verifying variant reads into a second buffer and compares, at the cost of
    a larger working set.
    """
    cfg = shaped_config(port, cap_mbps)
    conn = (
        StripedConnection(cfg, streams=streams)
        if streams > 1
        else InfinityConnection(cfg)
    )
    conn.connect()
    n = nbytes // BLOCK
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    conn.register_mr(src)
    dst = src
    if verify:
        dst = np.zeros_like(src)
        conn.register_mr(dst)
    pairs = [(f"{key_prefix}{streams}-{i}", i * BLOCK) for i in range(n)]

    async def once():
        await conn.write_cache_async(pairs, BLOCK, src.ctypes.data)
        await conn.read_cache_async(pairs, BLOCK, dst.ctypes.data)

    t0 = time.perf_counter()
    asyncio.run(once())
    dt = time.perf_counter() - t0
    verified = bool(np.array_equal(src, dst)) if verify else None
    conn.close()
    return 2 * n * BLOCK / dt / (1 << 20), verified
