"""Shared rate-shaped measurement helper.

One implementation of the shaped striped roundtrip, used by ``bench.py``,
``tools/striping_emulation.py``, and ``tests/test_striping_shaped.py`` — the
three must measure the same workload or the bench, tool, and CI test silently
diverge. The shaping itself is ``pacing_rate_mbps`` (SO_MAX_PACING_RATE, TCP
internal pacing): the client knob caps PUT egress, the server knob caps GET
egress, together emulating a bandwidth-limited cross-host stream on loopback.
"""

import asyncio
import time
from typing import Optional, Tuple

import numpy as np

from . import wire
from .config import ClientConfig
from .lib import InfinityConnection, StripedConnection

BLOCK = 64 << 10


def shaped_config(port: int, cap_mbps: Optional[int]) -> ClientConfig:
    """Loopback client config with per-connection pacing and shm disabled
    (every byte rides the paced socket).

    ``cap_mbps`` of ``None`` or ``0`` means UNSHAPED: pacing off, but shm
    still off — the socket path without a bandwidth cap, the config the
    shaping edge-case tests pin (a zero cap must be a no-op, not a stall).

    Shm staying off also matters for the ADAPTIVE striped scheduler
    (lib.StripedConnection): its same-host detector keys on the shm fast
    path being active, so a shaped connection never auto-collapses to one
    stripe — pacing emulates a cross-host link and the scheduler must keep
    striping it, merely shrinking each stripe's chunks to the paced rate
    (throughput EWMA x target chunk latency)."""
    return ClientConfig(
        host_addr="127.0.0.1",
        service_port=port,
        log_level="error",
        enable_shm=False,  # force the socket path: that is what stripes split
        pacing_rate_mbps=int(cap_mbps or 0),
    )


def shaped_roundtrip_mbps(
    port: int,
    cap_mbps: Optional[int],
    streams: int,
    nbytes: int,
    key_prefix: str = "shaped",
    verify: bool = False,
    stats_out: Optional[dict] = None,
) -> Tuple[float, Optional[bool]]:
    """Aggregate write+read MB/s of the headline workload over N paced
    stripes against the (server-side paced) store on ``port``.

    Returns (mbps, verified): ``verified`` is None unless ``verify`` — the
    verifying variant reads into a second buffer and compares, at the cost of
    a larger working set. When ``stats_out`` is given and the connection is
    striped, the adaptive scheduler's ``data_plane_stats()`` snapshot is
    copied into it after the measurement (per-stripe chunk counts + EWMA —
    how the tests see that pacing shrank the chunks rather than starving a
    stripe).
    """
    cfg = shaped_config(port, cap_mbps)
    conn = (
        StripedConnection(cfg, streams=streams)
        if streams > 1
        else InfinityConnection(cfg)
    )
    conn.connect()
    n = nbytes // BLOCK
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    conn.register_mr(src)
    dst = src
    if verify:
        dst = np.zeros_like(src)
        conn.register_mr(dst)
    pairs = [(f"{key_prefix}{streams}-{i}", i * BLOCK) for i in range(n)]

    async def once():
        # Explicitly FOREGROUND (qos_kwargs encodes nothing for class 0):
        # the shaped roundtrip measures the untagged wire path byte-for-byte.
        fg = wire.qos_kwargs(conn, wire.PRIORITY_FOREGROUND)
        await conn.write_cache_async(pairs, BLOCK, src.ctypes.data, **fg)
        await conn.read_cache_async(pairs, BLOCK, dst.ctypes.data, **fg)

    t0 = time.perf_counter()
    asyncio.run(once())
    dt = time.perf_counter() - t0
    verified = bool(np.array_equal(src, dst)) if verify else None
    if stats_out is not None and hasattr(conn, "data_plane_stats"):
        stats_out.update(conn.data_plane_stats())
    conn.close()
    return 2 * n * BLOCK / dt / (1 << 20), verified
