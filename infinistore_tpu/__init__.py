"""infinistore_tpu: a TPU-native distributed KV-cache store for LLM inference.

Brand-new framework with the capabilities of InfiniStore (reference surface:
reference infinistore/__init__.py:1-33), redesigned for TPU: the data
plane is zero-copy DCN socket I/O against pinned host-DRAM pools (no ibverbs).
"""

from .config import (
    LINK_DCN,
    LINK_ETHERNET,
    LINK_IB,
    LINK_ICI,
    TYPE_DCN,
    TYPE_RDMA,
    TYPE_TCP,
    ClientConfig,
    ServerConfig,
)
from .lib import (
    InfiniStoreColdTier,
    InfiniStoreException,
    InfiniStoreKeyNotFound,
    InfiniStoreNoMatch,
    InfiniStoreResourcePressure,
    InfinityConnection,
    StripedConnection,
    Logger,
    evict_cache,
    get_kvmap_len,
    get_server_stats,
    purge_kv_map,
    register_server,
    start_local_server,
    unregister_server,
)

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy: the connector/engine layers pull in jax (via the TPU data
    # plane); the core client/server API must stay importable without it.
    if name in ("KVConnector", "token_chain_hashes", "FetchCoalescer"):
        from . import connector

        return getattr(connector, name)
    if name in ("LayerwisePrefetch", "PrefetchDiscarded"):
        from .tpu import layerwise

        return getattr(layerwise, name)
    if name in ("StagingPoolExhausted", "StagingLease", "HostStagingPool"):
        from .tpu import staging

        return getattr(staging, name)
    if name == "KVLoadUnderDelivery":
        from . import vllm_v1

        return vllm_v1.KVLoadUnderDelivery
    if name in ("EngineKVAdapter", "ContinuousBatchingHarness", "BlockPool"):
        from . import engine

        return getattr(engine, name)
    if name in (
        "ClusterKVConnector",
        "rendezvous_owner",
        "rendezvous_ranked",
        "CircuitBreaker",
    ):
        from . import cluster

        return getattr(cluster, name)
    if name in ("MemberState", "MembershipView", "Membership", "Resharder"):
        from . import membership

        return getattr(membership, name)
    if name in (
        "TierPolicy",
        "TierPolicyConfig",
        "TierManager",
        "TemperatureSketch",
        "TIERS",
    ):
        from . import tiering

        return getattr(tiering, name)
    if name in (
        "DisaggHarness",
        "DisaggCounters",
        "stream_prefill",
        "overlapped_decode",
        "local_decode",
    ):
        from . import disagg

        return getattr(disagg, name)
    if name in ("FaultRule", "FaultyConnection", "kill_transport"):
        from . import faults

        return getattr(faults, name)
    if name in (
        "InfiniStoreKVConnectorV1",
        "KVConnectorRole",
        "KVConnectorMetadata",
    ):
        from . import vllm_v1

        return getattr(vllm_v1, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "KVConnector",
    "token_chain_hashes",
    "ClusterKVConnector",
    "rendezvous_owner",
    "rendezvous_ranked",
    "CircuitBreaker",
    "MemberState",
    "MembershipView",
    "Membership",
    "Resharder",
    "TierPolicy",
    "TierPolicyConfig",
    "TierManager",
    "TemperatureSketch",
    "TIERS",
    "DisaggHarness",
    "DisaggCounters",
    "stream_prefill",
    "overlapped_decode",
    "local_decode",
    "FaultRule",
    "FaultyConnection",
    "kill_transport",
    "EngineKVAdapter",
    "ContinuousBatchingHarness",
    "BlockPool",
    "InfiniStoreKVConnectorV1",
    "KVConnectorRole",
    "KVConnectorMetadata",
    "KVLoadUnderDelivery",
    "FetchCoalescer",
    "LayerwisePrefetch",
    "PrefetchDiscarded",
    "StagingPoolExhausted",
    "StagingLease",
    "HostStagingPool",
    "InfinityConnection",
    "StripedConnection",
    "register_server",
    "start_local_server",
    "unregister_server",
    "ClientConfig",
    "ServerConfig",
    "TYPE_RDMA",
    "TYPE_TCP",
    "TYPE_DCN",
    "Logger",
    "LINK_ETHERNET",
    "LINK_IB",
    "LINK_DCN",
    "LINK_ICI",
    "purge_kv_map",
    "get_kvmap_len",
    "get_server_stats",
    "InfiniStoreException",
    "InfiniStoreKeyNotFound",
    "InfiniStoreNoMatch",
    "InfiniStoreResourcePressure",
    "InfiniStoreColdTier",
    "evict_cache",
]
