"""Elastic cluster membership: live add/remove of members with online
rendezvous-delta resharding.

``ClusterKVConnector`` (cluster.py) fixed its member list at construction —
scaling the pool meant draining it. This module is the step from "a
cluster" to "a fleet": a **versioned membership view** that changes at
runtime while reads stay available, and a **resharder** that moves only the
keys whose rendezvous placement actually changed (Beluga's pooled, scalable
KVCache shape, PAPERS.md; Mooncake-style background movement).

Three pieces:

- :class:`MembershipView` — an immutable, **epoch-stamped** snapshot of the
  member list and per-member state. Every mutation produces a new view with
  a higher epoch; readers hold a view and can never observe a half-applied
  transition.
- :class:`Membership` — the state machine. Members move through
  ``JOINING -> ACTIVE -> LEAVING -> REMOVED`` (graceful) or ``-> DEAD``
  (crash). Placement (where NEW writes go) covers JOINING+ACTIVE members;
  reads may also fall back to LEAVING members until their migration drains.
  Entry indices are **stable forever** (tombstones, never deletion), so the
  cluster's per-member breaker/health arrays stay index-aligned across any
  amount of churn.
- :class:`Resharder` — a background reconciler. It owns no policy of its
  own: the target placement of every root is ``rendezvous_ranked`` over the
  current view's placement ids (cluster.py), so the **delta between epochs
  is computed, not configured** — a join moves only the ~1/(N+1) of roots
  whose owner/replica set gained the joiner; a leave/death re-mirrors only
  the leaver's roots from their surviving replica to the promoted
  successor. Migration traffic is tagged ``PRIORITY_BACKGROUND`` end to end
  (docs/qos.md) so a reshard cannot move the foreground p99, and every
  transport error routes through the cluster's degrade machinery
  (``_begin``/``_done`` — the same breakers ordinary ops feed;
  docs/robustness.md). An epoch change mid-pass triggers a **replan**, so a
  member dying during a reshard is re-planned against the new view instead
  of wedging the old plan.

Availability during a reshard is the cluster's job (epoch-aware read
failover: try the new owner, fall back to the old owner/replica —
cluster.py ``_read_candidates``); this module's job is that the fallback
window closes: when the reconciler drains, it **finalizes** the pending
transitions (JOINING becomes ACTIVE, LEAVING becomes REMOVED) and the view
collapses back to a single placement.

See docs/membership.md for the protocol walk-through.
"""

import json
import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .lib import (
    InfiniStoreException,
    InfiniStoreKeyNotFound,
    InfiniStoreResourcePressure,
    Logger,
)
from . import telemetry
from .wire import PRIORITY_BACKGROUND

__all__ = [
    "MemberState", "MembershipView", "Membership", "Resharder", "DurableLog",
]


# ---------------------------------------------------------------------------
# Durable write-ahead log (crash-safe catalog + reshard journal).
# ---------------------------------------------------------------------------

# On-disk record framing: little-endian u32 payload length + u32 CRC32 of
# the payload, then the JSON payload bytes. The header carries no magic —
# the file IS the stream, and replay validates every record by checksum.
_REC_HDR = struct.Struct("<II")


class DurableLog:
    """Append-only, length-prefixed, checksummed, fsync-bounded record log
    — the durability substrate for the cluster's root catalog and reshard
    journal (docs/membership.md, durability section).

    Write path: each :meth:`append` frames one JSON record as
    ``[u32 length][u32 crc32(payload)][payload]``, writes it through the
    buffered file and flushes to the OS (a ``kill -9`` therefore loses
    nothing already appended); ``fsync`` is **bounded**, not per-record —
    at most one fsync per ``fsync_interval_s`` unless the caller forces it
    (membership transitions and reshard plan records do; per-save catalog
    records do not), so journaling stays off the save path's latency.

    Replay policy (:meth:`replay`):

    - a **torn tail** (truncated header or payload — the record being
      written when the process died) is discarded cleanly and counted
      (``journal_replay_torn``), never parsed;
    - a record whose **checksum mismatches** is skipped and counted
      (``journal_replay_bad_checksum``); replay continues at the next
      frame (the length prefix still delimits it). A corrupted *length*
      field cannot be resynced past — the remainder is treated as a torn
      tail;
    - everything else replays in append order (last record wins per key,
      so a ``drop`` tombstone after a ``root`` record keeps the root
      dropped — replay can never resurrect it).

    :meth:`compact` atomically rewrites the log as a snapshot (tmp file +
    fsync + ``os.replace``), preserving holder block-levels and membership
    tombstones while discarding the superseded incremental records — run
    on reshard finalize and at replay time.

    Thread-safe: one internal lock serializes appends/compaction (event
    loop, resharder worker and operator threads all write).

    ``status()`` keys (exported as ``infinistore_journal_*`` on /metrics,
    ITS-C005): ``journal_records``, ``journal_bytes``, ``journal_fsyncs``,
    ``journal_compactions``, ``journal_replay_records``,
    ``journal_replay_torn``, ``journal_replay_bad_checksum``.
    """

    MAX_RECORD = 16 << 20  # length-field plausibility bound for replay

    def __init__(self, path: str, fsync_interval_s: float = 0.05,
                 clock=time.monotonic):
        # its: cross-thread  (event loop, resharder worker and operator
        # threads all append; compaction runs from the worker)
        self.path = path
        self.fsync_interval_s = fsync_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        # its: guard[_f, _last_fsync: _lock]
        self._f = open(path, "ab")
        self._last_fsync = clock()
        # its: guard[records, fsyncs, compactions: _lock!w]
        self.records = 0
        self.fsyncs = 0
        self.compactions = 0
        self.replay_records = 0
        self.replay_torn = 0
        self.replay_bad_checksum = 0

    @staticmethod
    def _frame(record: dict) -> bytes:
        payload = json.dumps(record, separators=(",", ":")).encode()
        return _REC_HDR.pack(len(payload), zlib.crc32(payload)) + payload

    def append(self, record: dict, fsync: bool = False):
        """Append one record (write + flush to the OS always; fsync when
        forced or the bounded interval elapsed). No-op after close()."""
        buf = self._frame(record)
        with self._lock:
            if self._f is None:
                return
            self._f.write(buf)
            # Audited: a bounded buffered write + flush to the page cache
            # (microseconds; the journal lives on tmpfs in every harness).
            # The fsync below is interval-bounded and forced only from
            # non-loop paths (transitions, reshard plans).
            self._f.flush()
            self.records += 1
            now = self._clock()
            if fsync or now - self._last_fsync >= self.fsync_interval_s:
                os.fsync(self._f.fileno())
                self._last_fsync = now
                self.fsyncs += 1

    def replay(self) -> List[dict]:
        """Parse every intact record from disk, applying the torn-tail /
        bad-checksum policy above; updates the replay counters."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return []
        out: List[dict] = []
        torn = bad = 0
        i = 0
        n = len(data)
        while i < n:
            if i + _REC_HDR.size > n:
                torn += 1  # partial header: the frame being written
                break
            ln, crc = _REC_HDR.unpack_from(data, i)
            if ln <= 0 or ln > self.MAX_RECORD:
                # Implausible length = corrupt frame boundary; nothing
                # after it can be delimited — discard as a torn tail.
                torn += 1
                break
            if i + _REC_HDR.size + ln > n:
                torn += 1  # partial payload
                break
            payload = data[i + _REC_HDR.size: i + _REC_HDR.size + ln]
            i += _REC_HDR.size + ln
            if zlib.crc32(payload) != crc:
                bad += 1  # skipped, counted; next frame still delimited
                continue
            try:
                out.append(json.loads(payload))
            except ValueError:
                bad += 1
        self.replay_records = len(out)
        self.replay_torn = torn
        self.replay_bad_checksum = bad
        return out

    def compact(self, records):
        """Atomically replace the log's contents with ``records`` — either
        a sequence of record dicts or a CALLABLE returning one: tmp file,
        fsync, ``os.replace``, append order preserved.

        Pass a callable when the snapshot derives from live state the
        appenders also mutate (the cluster's catalog): it runs UNDER the
        log lock, so no append can land between the snapshot read and the
        file replace — otherwise a record written in that window (e.g. a
        ``drop`` tombstone racing a finalize-time compaction) would be
        silently destroyed with the old file, and a later replay would
        resurrect state the appender had already retired. Appenders must
        therefore never call :meth:`append` while holding a lock the
        snapshot function takes (the cluster appends outside its catalog
        lock, always)."""
        with self._lock:
            if self._f is None:
                return
            if callable(records):
                # The cluster's snapshot callable takes the catalog lock
                # HERE, under the log lock — the one blessed direction of
                # that pair. Summarized for the static lock-order graph
                # (the callback indirection hides it from inference):
                # its: acquires[ClusterKVConnector._cat_lock]
                records = records()
            tmp = self.path + ".compact.tmp"
            with open(tmp, "wb") as f:
                for r in records:
                    f.write(self._frame(r))
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")
            self._last_fsync = self._clock()
            self.compactions += 1
            self.fsyncs += 1

    def size_bytes(self) -> int:
        try:
            return os.stat(self.path).st_size
        except OSError:
            return 0

    def status(self) -> dict:
        """Flat ``journal_*`` counter snapshot for /membership + /metrics.

        Keys: ``journal_records`` (appends, lifetime), ``journal_bytes``
        (current log size), ``journal_fsyncs``, ``journal_compactions``,
        ``journal_replay_records`` / ``journal_replay_torn`` /
        ``journal_replay_bad_checksum`` (what the startup replay saw)."""
        return {
            "journal_records": self.records,
            "journal_bytes": self.size_bytes(),
            "journal_fsyncs": self.fsyncs,
            "journal_compactions": self.compactions,
            "journal_replay_records": self.replay_records,
            "journal_replay_torn": self.replay_torn,
            "journal_replay_bad_checksum": self.replay_bad_checksum,
        }

    def close(self):
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()
            self._f = None


class MemberState:
    """Member lifecycle states (docs/membership.md):

    - ``JOINING``: in placement (new writes target it; the resharder is
      copying its share of existing roots); readable.
    - ``ACTIVE``: steady state — in placement, readable.
    - ``LEAVING``: graceful drain — OUT of placement (no new writes), still
      readable while the resharder re-mirrors its roots to their promoted
      successors.
    - ``DEAD``: crash — out of placement, NOT readable; its copies are
      written off and re-replicated from surviving replicas.
    - ``REMOVED``: terminal tombstone after a LEAVING member's drain
      completes. Kept so entry indices stay stable forever.
    """

    JOINING = "joining"
    ACTIVE = "active"
    LEAVING = "leaving"
    DEAD = "dead"
    REMOVED = "removed"

    # States that take NEW writes (rendezvous placement targets).
    PLACEMENT = (JOINING, ACTIVE)
    # States reads may still be served from.
    READABLE = (JOINING, ACTIVE, LEAVING)
    # Terminal states (no further transitions).
    TERMINAL = (DEAD, REMOVED)


@dataclass(frozen=True)
class MembershipView:
    """Immutable epoch-stamped membership snapshot.

    ``member_ids``/``states`` are index-aligned with the owning cluster's
    member arrays — indices are stable across churn (tombstoned, never
    reused), so a view captured before a transition still resolves
    correctly after it.
    """

    epoch: int
    member_ids: Tuple[str, ...]
    states: Tuple[str, ...]
    # Per-entry incarnation stamp: the epoch at which the entry reached its
    # current state. Gossip merges compare (since_epoch, state rank) so a
    # DEAD tombstone at epoch 5 beats stale ACTIVE knowledge from epoch 3
    # while a legitimate re-add at epoch 7 beats the tombstone
    # (docs/membership.md, gossip section). Empty for views built by old
    # callers; zip() below tolerates it.
    since: Tuple[int, ...] = ()

    def placement_ids(self) -> List[str]:
        """Member ids new writes rendezvous over (JOINING + ACTIVE)."""
        return [
            m for m, s in zip(self.member_ids, self.states)
            if s in MemberState.PLACEMENT
        ]

    def readable_ids(self) -> List[str]:
        """Member ids reads may be served from (placement + LEAVING)."""
        return [
            m for m, s in zip(self.member_ids, self.states)
            if s in MemberState.READABLE
        ]

    def state_of(self, member_id: str) -> Optional[str]:
        """Current state of ``member_id`` (None when unknown). When an id
        was re-added after death, the LATEST entry wins."""
        for m, s in zip(reversed(self.member_ids), reversed(self.states)):
            if m == member_id:
                return s
        return None

    def as_dict(self) -> dict:
        """JSON-shaped view for health()/the manage plane (and the gossip
        exchange payload — ``since_epoch`` is what makes the merge
        tombstone-aware)."""
        since = self.since or (0,) * len(self.member_ids)
        return {
            "epoch": self.epoch,
            "members": [
                {"member_id": m, "state": s, "since_epoch": int(se)}
                for m, s, se in zip(self.member_ids, self.states, since)
            ],
        }


@dataclass
class _Entry:
    member_id: str
    state: str
    since_epoch: int


class Membership:
    """The versioned membership state machine.

    Thread-safe: every transition happens under one lock and bumps
    ``epoch``; readers take :meth:`view` (immutable). The previous
    placement id set is retained from the moment the view diverges until
    :meth:`finalize_transitions` collapses it — that window is what the
    cluster's epoch-aware read failover spans (reads try the new owner,
    then the old owner/replica), so availability stays 1.0 mid-reshard.

    Transitions (anything else raises ``ValueError``):

    - ``add_member(id)``: new entry JOINING (id must not collide with a
      live entry; DEAD/REMOVED tombstone ids may rejoin as a new entry).
    - ``remove_member(id)``: JOINING/ACTIVE -> LEAVING.
    - ``mark_dead(id)``: JOINING/ACTIVE/LEAVING -> DEAD.
    - ``finalize_transitions()``: JOINING -> ACTIVE, LEAVING -> REMOVED —
      called by the :class:`Resharder` once migration for the current
      epoch drained.
    """

    def __init__(self, member_ids: Sequence[str], clock=time.monotonic):
        if not member_ids:
            raise ValueError("membership needs at least one member")
        if len(set(member_ids)) != len(member_ids):
            raise ValueError(f"member_ids must be unique, got {list(member_ids)}")
        self._lock = threading.Lock()
        self._clock = clock
        # The published-snapshot discipline (ITS-R001): every mutation
        # happens under _lock and republishes _view; readers take the
        # immutable view (or a single-reference read) lock-free.
        # its: guard[epoch, epoch_changes, _entries: _lock!w]
        self.epoch = 1
        self._entries: List[_Entry] = [
            _Entry(mid, MemberState.ACTIVE, 1) for mid in member_ids
        ]
        self.epoch_changes = 0  # transitions applied (counter, not gauge)
        # Placement ids as of the last SETTLED view; the read-failover
        # fallback set while a transition is in flight. None when settled.
        # its: guard[_prev_placement, _owner, _view: _lock!w]
        self._prev_placement: Optional[Tuple[str, ...]] = None
        # True while THIS process originated the pending transition: only
        # the originator finalizes (a gossip adopter with an empty catalog
        # must not rubber-stamp a transition whose migration it cannot
        # see — it settles when the originator's finalized view arrives).
        self._owner = False
        # Post-publish hook (cluster journaling): called with the new view
        # after every epoch change, OUTSIDE the membership lock.
        self.on_change: Optional[Callable[[MembershipView], None]] = None
        self._view = self._snapshot()

    # -- snapshots -----------------------------------------------------------

    def _snapshot(self) -> MembershipView:
        return MembershipView(
            epoch=self.epoch,
            member_ids=tuple(e.member_id for e in self._entries),
            states=tuple(e.state for e in self._entries),
            since=tuple(e.since_epoch for e in self._entries),
        )

    def _notify(self, view: MembershipView):
        cb = self.on_change
        if cb is not None:
            try:
                cb(view)
            except Exception as e:  # journaling must never fail a transition
                Logger.error(f"membership on_change hook failed: {e!r}")

    def view(self) -> MembershipView:
        """The current immutable view (cheap: prebuilt per transition)."""
        return self._view

    @property
    def settled(self) -> bool:
        """True when no transition is pending (no JOINING/LEAVING entry)."""
        v = self._view
        return not any(
            s in (MemberState.JOINING, MemberState.LEAVING) for s in v.states
        )

    @property
    def prev_placement(self) -> Optional[Tuple[str, ...]]:
        """Placement ids of the last settled view while a transition is in
        flight (the old owners reads fall back to), else None."""
        return self._prev_placement

    def index_of(self, member_id: str) -> int:
        """Stable entry index of ``member_id`` (latest entry when a
        tombstoned id rejoined). Raises KeyError when unknown."""
        for i in range(len(self._entries) - 1, -1, -1):
            if self._entries[i].member_id == member_id:
                return i
        raise KeyError(member_id)

    # -- transitions ---------------------------------------------------------

    def _entry(self, member_id: str) -> _Entry:
        return self._entries[self.index_of(member_id)]

    def _mutate(self, fn, action: str = "", member_id: str = "") -> MembershipView:
        with self._lock:
            if self._prev_placement is None:
                self._prev_placement = tuple(self._view.placement_ids())
            fn()
            self.epoch += 1
            self.epoch_changes += 1
            self._owner = True  # this process originated the transition
            self._view = view = self._snapshot()
        # Journal the epoch bump OUTSIDE the membership lock (the journal
        # has its own): which transition, on whom, to which epoch — the
        # causal anchor reshard/failover traces hang from
        # (docs/observability.md).
        telemetry.emit(
            "membership_epoch", member=member_id, epoch=view.epoch,
            action=action,
        )
        self._notify(view)
        return view

    def add_member(self, member_id: str) -> MembershipView:
        """Admit ``member_id`` as JOINING (it immediately takes new writes;
        the resharder copies its rendezvous share of existing roots)."""
        def apply():  # its: requires[_lock]
            try:
                live = self._entry(member_id).state
            except KeyError:
                live = None
            if live is not None and live not in MemberState.TERMINAL:
                raise ValueError(
                    f"member {member_id!r} already present ({live})"
                )
            self._entries.append(
                _Entry(member_id, MemberState.JOINING, self.epoch + 1)
            )
        return self._mutate(apply, action="add", member_id=member_id)

    def remove_member(self, member_id: str) -> MembershipView:
        """Begin a graceful drain: ``member_id`` leaves placement (no new
        writes) but stays readable until its roots are re-mirrored.
        Refused for the LAST placement member — a graceful drain promises
        the data survives, and there would be nowhere to re-mirror it
        (``mark_dead`` remains available to record a real crash)."""
        def apply():  # its: requires[_lock]
            e = self._entry(member_id)
            if e.state not in (MemberState.JOINING, MemberState.ACTIVE):
                raise ValueError(
                    f"cannot remove member {member_id!r} in state {e.state}"
                )
            survivors = [
                o for o in self._entries
                if o is not e and o.state in MemberState.PLACEMENT
            ]
            if not survivors:
                raise ValueError(
                    f"cannot remove {member_id!r}: it is the last placement "
                    "member — nowhere to re-mirror its roots (add a member "
                    "first, or mark_dead to record a crash)"
                )
            e.state = MemberState.LEAVING
            e.since_epoch = self.epoch + 1
        return self._mutate(apply, action="remove", member_id=member_id)

    def mark_dead(self, member_id: str) -> MembershipView:
        """Write a member off: out of placement AND unreadable. Its copies
        are lost; the resharder re-replicates from surviving replicas."""
        def apply():  # its: requires[_lock]
            e = self._entry(member_id)
            if e.state in MemberState.TERMINAL:
                raise ValueError(
                    f"member {member_id!r} already terminal ({e.state})"
                )
            e.state = MemberState.DEAD
            e.since_epoch = self.epoch + 1
        return self._mutate(apply, action="mark_dead", member_id=member_id)

    def finalize_transitions(
        self, expected_epoch: Optional[int] = None
    ) -> Optional[MembershipView]:
        """Collapse pending transitions once migration drained: JOINING ->
        ACTIVE, LEAVING -> REMOVED, and drop the fallback placement set.
        Returns the new view, or None when nothing was pending (no epoch
        bump). ``expected_epoch``: refuse (return None, no change) unless
        the epoch still equals it — the resharder passes the epoch it
        PLANNED at, so a transition landing between plan and finalize can
        never be finalized with zero migration done (the next pass replans
        it instead). Resharder-internal in normal operation."""
        with self._lock:
            if expected_epoch is not None and self.epoch != expected_epoch:
                return None
            changed = False
            for e in self._entries:
                moved = e.state in (MemberState.JOINING, MemberState.LEAVING)
                if e.state == MemberState.JOINING:
                    e.state = MemberState.ACTIVE
                elif e.state == MemberState.LEAVING:
                    e.state = MemberState.REMOVED
                if moved:
                    changed = True
                    e.since_epoch = self.epoch + 1
            self._prev_placement = None
            if not changed:
                self._owner = False
                return None
            self.epoch += 1
            self.epoch_changes += 1
            self._owner = False
            self._view = view = self._snapshot()
        telemetry.emit(
            "membership_epoch", epoch=view.epoch, action="finalize",
        )
        self._notify(view)
        return view

    # -- gossip merge + restore (docs/membership.md) -------------------------

    # Per-entry precedence within one incarnation (equal since_epoch): a
    # more advanced state wins, and terminal states dominate liveness — a
    # lattice join, so concurrent merges commute and every process
    # converges on identical states without coordination.
    _STATE_RANK = {
        MemberState.JOINING: 1,
        MemberState.ACTIVE: 2,
        MemberState.LEAVING: 3,
        MemberState.DEAD: 4,
        MemberState.REMOVED: 5,
    }

    @property
    def owns_transition(self) -> bool:
        """True while the pending transition was originated by THIS process
        (only the originator's resharder finalizes it; gossip adopters
        settle when the finalized view arrives)."""
        return self._owner

    @classmethod
    def _beats(cls, a_state: str, a_since: int, b_state: str,
               b_since: int) -> bool:
        """Does (b_state @ b_since) supersede (a_state @ a_since)? Newer
        incarnation wins outright (a re-add after DEAD is legitimate);
        within one incarnation the state lattice decides (tombstones
        dominate — stale liveness never resurrects a written-off member)."""
        if b_since != a_since:
            return b_since > a_since
        return cls._STATE_RANK.get(b_state, 0) > cls._STATE_RANK.get(a_state, 0)

    @staticmethod
    def _latest_remote(remote_members: Sequence[dict]) -> Dict[str, Tuple[str, int]]:
        latest: Dict[str, Tuple[str, int]] = {}
        for m in remote_members:
            mid = m["member_id"]
            state = m["state"]
            since = int(m.get("since_epoch", 0))
            cur = latest.get(mid)
            if cur is None or Membership._beats(cur[0], cur[1], state, since):
                latest[mid] = (state, since)
        return latest

    def _merge_delta(self, remote_members: Sequence[dict]):
        """(in-place state changes, brand-new entries) the lattice join of
        the current entries with a remote view would apply. Caller holds
        ``self._lock``. New entries come back in a deterministic order
        (sorted by (since_epoch, member_id)) so the cluster can append its
        member arrays in the same order it later re-derives here."""
        local_latest: Dict[str, int] = {}  # mid -> latest entry index
        for i, e in enumerate(self._entries):
            local_latest[e.member_id] = i
        changes: List[Tuple[int, str, int]] = []  # (entry idx, state, since)
        new: List[Tuple[str, str, int]] = []  # (mid, state, since)
        for mid, (rstate, rsince) in self._latest_remote(remote_members).items():
            idx = local_latest.get(mid)
            if idx is None:
                new.append((mid, rstate, rsince))
                continue
            e = self._entries[idx]
            if not self._beats(e.state, e.since_epoch, rstate, rsince):
                continue
            if e.state in MemberState.TERMINAL and rsince > e.since_epoch:
                # A newer incarnation of a tombstoned id: a NEW entry (the
                # dead incarnation's index stays stable forever).
                new.append((mid, rstate, rsince))
            else:
                changes.append((idx, rstate, rsince))
        new.sort(key=lambda t: (t[2], t[0]))
        return changes, new

    def merge_plan(self, remote_members: Sequence[dict]) -> List[Tuple[str, str, int]]:
        """Dry run of a gossip merge: the brand-new entries (in apply
        order) a :meth:`merge_apply` of this payload would append — the
        cluster dials connections for the readable ones first, then
        applies (docs/membership.md, gossip section)."""
        with self._lock:
            _, new = self._merge_delta(remote_members)
        return new

    def merge_apply(
        self, remote_members: Sequence[dict], remote_epoch: int,
        prev_placement: Optional[Sequence[str]] = None,
        on_new=None,
    ) -> Tuple[bool, MembershipView]:
        """Apply the tombstone-aware lattice merge of a remote view
        (docs/membership.md: per member id, the newest incarnation wins;
        within one incarnation the more advanced state wins, so terminal
        knowledge dominates). The epoch becomes ``max(local, remote)`` —
        the merge itself is commutative and idempotent, so two processes
        exchanging in either order converge on identical (epoch, states).
        Returns ``(changed, view)``. Does NOT take transition ownership:
        an adopted transition is finalized by its originator, and this
        process settles when the finalized view gossips back.

        ``on_new(member_id, state, since)``: called UNDER the membership
        lock immediately before each brand-new entry appends — the
        cluster appends its member/health array slots there, so entry
        indices and member arrays cannot diverge even when a concurrent
        finalize (the resharder thread holds no admin lock) changed the
        delta between the caller's ``merge_plan`` and this apply. Must be
        O(1) and non-blocking (no I/O, no other locks)."""
        with self._lock:
            changes, new = self._merge_delta(remote_members)
            epoch_moved = int(remote_epoch) > self.epoch
            if not changes and not new and not epoch_moved:
                return False, self._view
            was_placement = tuple(self._view.placement_ids())
            for idx, state, since in changes:
                self._entries[idx].state = state
                self._entries[idx].since_epoch = since
            for mid, state, since in new:
                if on_new is not None:
                    on_new(mid, state, since)
                self._entries.append(_Entry(mid, state, since))
            self.epoch = max(self.epoch, int(remote_epoch))
            self.epoch_changes += 1
            self._view = view = self._snapshot()
            settled = not any(
                s in (MemberState.JOINING, MemberState.LEAVING)
                for s in view.states
            )
            if settled:
                self._prev_placement = None
            elif self._prev_placement is None:
                # The fallback set reads span mid-transition: the sender's
                # pre-transition placement when it shared one, else our own
                # placement as of just before this merge.
                self._prev_placement = (
                    tuple(prev_placement) if prev_placement else was_placement
                )
        telemetry.emit(
            "membership_epoch", epoch=view.epoch, action="gossip_merge",
        )
        self._notify(view)
        return True, view

    def restore(
        self, entries: Sequence[Tuple[str, str, int]], epoch: int,
        prev_placement: Optional[Sequence[str]] = None, owner: bool = False,
    ) -> MembershipView:
        """Install a journaled view wholesale (crash-recovery replay;
        construction-time only — no epoch bump, no events, no hooks). The
        caller has already rebuilt its member arrays in ``entries``
        order."""
        with self._lock:
            self._entries = [
                _Entry(mid, state, int(since)) for mid, state, since in entries
            ]
            self.epoch = int(epoch)
            self._prev_placement = (
                tuple(prev_placement) if prev_placement else None
            )
            self._owner = bool(owner)
            self._view = view = self._snapshot()
        return view

    # -- observability -------------------------------------------------------

    def status(self) -> dict:
        """Flat counter snapshot for /membership, /metrics and health().

        Keys: ``membership_epoch`` (current view epoch),
        ``membership_epoch_changes`` (transitions applied),
        ``membership_members`` (live entries: placement + LEAVING),
        ``membership_joining`` / ``membership_active`` /
        ``membership_leaving`` / ``membership_dead`` /
        ``membership_removed`` (entries per state), and
        ``membership_settled`` (1 when no transition is pending)."""
        v = self._view
        by_state = {s: 0 for s in (
            MemberState.JOINING, MemberState.ACTIVE, MemberState.LEAVING,
            MemberState.DEAD, MemberState.REMOVED,
        )}
        for s in v.states:
            by_state[s] += 1
        return {
            "membership_epoch": v.epoch,
            "membership_epoch_changes": self.epoch_changes,
            "membership_members": len(v.readable_ids()),
            "membership_joining": by_state[MemberState.JOINING],
            "membership_active": by_state[MemberState.ACTIVE],
            "membership_leaving": by_state[MemberState.LEAVING],
            "membership_dead": by_state[MemberState.DEAD],
            "membership_removed": by_state[MemberState.REMOVED],
            "membership_settled": 1 if self.settled else 0,
        }


# ---------------------------------------------------------------------------
# Resharder
# ---------------------------------------------------------------------------


@dataclass
class _RootTask:
    """One root's migration work for the current epoch: copy its keys to
    the placement members that lack them, from any readable holder, then
    prune the copies rendezvous no longer wants."""

    root: str
    tokens: np.ndarray
    blocks: int
    sources: List[str]  # holder ids, rendezvous-rank order, readable only
    targets: List[str]  # placement ids missing the copy (want - holders)
    prune: List[str] = field(default_factory=list)  # holders no longer wanted


class _CopyError(Exception):
    """A migration copy failed, remembering WHICH side's transport did —
    the error must feed the failing member's breaker, not its innocent
    counterpart (a flaky source must never open a healthy destination's
    circuit)."""

    def __init__(self, side: str, cause: InfiniStoreException):
        super().__init__(f"{side}: {cause}")
        self.side = side  # "src" | "dst"
        self.cause = cause


class Resharder:
    """Background reconciler: drive the cluster's key placement to match the
    current membership view, one rendezvous delta at a time.

    The worker thread wakes on :meth:`kick` (every membership transition),
    plans the delta for the CURRENT epoch from the cluster's root catalog
    (cluster.py ``reshard_plan``), and executes it root by root:

    - read the root's keys from a readable holder (surviving replica /
      leaver / old owner) through that member's circuit breaker,
    - write them to each missing placement member (the joiner, or the
      promoted successor),
    - prune the copies rendezvous no longer wants (a moved root's old
      owner), so a join *moves* ~1/N of keys rather than accreting copies.

    All data-plane ops are **sync batched ops off any event loop**, tagged
    ``PRIORITY_BACKGROUND`` (ITS-P003 enforces the tag): the server's
    two-class scheduler and the client's process-wide foreground gate keep
    a reshard out of the foreground p99 (docs/qos.md). Transport errors
    feed the owning member's breaker via the cluster's ``_done`` — the
    degrade machinery sees migration traffic exactly like foreground
    traffic (ITS-P001). If the epoch changes mid-pass (a member died
    during the reshard), the pass aborts and **replans** against the new
    view; roots whose every holder is gone are written off (reads degrade
    to a miss — recompute, never wrong bytes).

    When a pass drains with zero debt, pending transitions finalize
    (``Membership.finalize_transitions``) and the worker idles.
    """

    def __init__(self, cluster, max_batch_bytes: int = 2 << 20,
                 retry_backoff_s: float = 0.05, clock=time.monotonic):
        self.cluster = cluster
        self.max_batch_bytes = max_batch_bytes
        self.retry_backoff_s = retry_backoff_s
        self._clock = clock
        self._cv = threading.Condition()
        # its: guard[_dirty: _cv]
        self._dirty = False
        # its: guard[_stop, _active: _cv!w]
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._active = False  # worker mid-pass or debt outstanding
        # Counters (reshard_* vocabulary — docs/membership.md). Written
        # only on the reconciler thread (cluster.reshard_plan's
        # lost-roots bump runs there too); progress() snapshots them.
        # its: guard[_c: single_writer]
        self._c = {
            "reshard_passes": 0,
            "reshard_replans": 0,
            "reshard_planned_roots": 0,
            "reshard_moved_roots": 0,
            "reshard_moved_keys": 0,
            "reshard_moved_bytes": 0,
            "reshard_pruned_keys": 0,
            "reshard_skipped_keys": 0,
            "reshard_failed_roots": 0,
            "reshard_lost_roots": 0,
            "reshard_debt_roots": 0,
            "reshard_prune_debt": 0,
            "reshard_last_pass_ms": 0.0,
        }

    # -- lifecycle -----------------------------------------------------------

    def kick(self):
        """Wake the reconciler (the cluster calls this on every membership
        transition; saves do NOT kick — an under-replicated save is
        reconciled on the next transition's pass, matching the pre-elastic
        replication contract). Starts the worker thread lazily on first
        use."""
        with self._cv:
            self._dirty = True
            self._active = True
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="its-resharder", daemon=True
                )
                self._thread.start()
            self._cv.notify_all()

    def stop(self):
        """Stop the worker (the cluster's close path); idempotent."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    @property
    def active(self) -> bool:
        """True while migration work is planned, running, or pending."""
        return self._active

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the reconciler drained (no debt, membership settled)
        or ``timeout`` elapsed; returns True when idle."""
        deadline = self._clock() + timeout
        with self._cv:
            while self._active or self._dirty:
                left = deadline - self._clock()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    # -- observability -------------------------------------------------------

    def progress(self) -> dict:
        """Flat migration counters for /membership, /metrics and health().

        Keys: ``reshard_active`` (1 while migrating), ``reshard_passes``
        (reconcile sweeps), ``reshard_replans`` (passes aborted by an epoch
        change), ``reshard_planned_roots`` (delta tasks planned, lifetime),
        ``reshard_moved_roots`` / ``reshard_moved_keys`` /
        ``reshard_moved_bytes`` (migration volume),
        ``reshard_pruned_keys`` (copies deleted where rendezvous no longer
        places the root), ``reshard_skipped_keys`` (keys evicted under the
        copy — skipped, never fabricated), ``reshard_failed_roots`` (tasks
        that failed a pass and stayed as debt), ``reshard_lost_roots``
        (roots written off: every holder dead), ``reshard_debt_roots``
        (remaining COPY delta after the last pass — the bounded migration
        debt the bench gates at 0), ``reshard_prune_debt`` (stale copies
        whose delete could not land yet — space, not correctness; retried
        on later passes without blocking convergence),
        ``reshard_last_pass_ms``, and ``reshard_catalog_roots`` (live root
        records in the cluster's catalog — the knowledge a crash-restart
        recovers from the durable journal, docs/membership.md)."""
        out = dict(self._c)
        out["reshard_active"] = 1 if self._active else 0
        out["reshard_catalog_roots"] = len(getattr(self.cluster, "_catalog", ()))
        return out

    # -- worker --------------------------------------------------------------

    def _run(self):
        backoff = self.retry_backoff_s
        while True:
            with self._cv:
                while not self._dirty and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                self._dirty = False
            try:
                debt = self._reconcile()
            except Exception as e:  # never let the reconciler thread die
                Logger.error(f"resharder pass failed: {e!r}")
                debt = 1
            with self._cv:
                if debt and not self._stop:
                    # Failed roots stay as debt: retry with a light backoff
                    # (a kicked epoch change interrupts the sleep). A timed
                    # backoff, not a predicate wait: a spurious wake only
                    # retries the pass sooner, and the loop-top while
                    # re-checks _dirty/_stop before the next sleep.
                    self._dirty = True
                    self._cv.wait(timeout=backoff)  # its: allow[ITS-R004]
                    backoff = min(backoff * 2.0, 1.0)
                else:
                    backoff = self.retry_backoff_s
                    if not self._dirty:
                        self._active = False
                self._cv.notify_all()

    def _reconcile(self) -> int:
        """One reconcile sweep: plan the delta at the current epoch and
        execute it; returns the remaining debt (0 = drained). An epoch
        change mid-pass aborts and reports the rest as debt (the next pass
        replans against the new view)."""
        membership: Membership = self.cluster.membership
        t0 = self._clock()
        epoch = membership.view().epoch
        tasks = self.cluster.reshard_plan()
        self._c["reshard_passes"] += 1
        self._c["reshard_planned_roots"] += len(tasks)
        self._c["reshard_debt_roots"] = len(tasks)
        # Journal the pass (docs/membership.md, durability): a restarted
        # client sees an OPEN plan record (no matching "fin") and resumes
        # the migration from the journaled catalog instead of waiting for
        # the next transition. Holder updates per copied root double as
        # the progress records — a replayed plan only contains the roots
        # still missing copies.
        journal = getattr(self.cluster, "journal_reshard_event", None)
        if tasks and journal is not None:
            journal("plan", epoch, len(tasks))
        debt = 0
        prune_debt = 0
        for k, task in enumerate(tasks):
            if self._stop:
                return len(tasks) - k
            if membership.view().epoch != epoch:
                # The view moved under us (e.g. a member died mid-reshard):
                # this plan is stale — abort and replan at the new epoch.
                self._c["reshard_replans"] += 1
                self._c["reshard_debt_roots"] = len(tasks) - k
                self._c["reshard_prune_debt"] = prune_debt
                return len(tasks) - k
            ok, prune_failed = self._migrate_root(task)
            prune_debt += prune_failed
            if ok:
                if task.targets:
                    # Prune-only retries (copy landed in an earlier pass)
                    # are not a second "move" — the bench's moved-fraction
                    # gate counts roots, not passes.
                    self._c["reshard_moved_roots"] += 1
            else:
                self._c["reshard_failed_roots"] += 1
                debt += 1
            self._c["reshard_debt_roots"] = debt + (len(tasks) - 1 - k)
        self._c["reshard_debt_roots"] = debt
        self._c["reshard_prune_debt"] = prune_debt
        if debt == 0:
            if tasks and journal is not None:
                # Close the journaled plan: this pass's copy debt drained
                # (fin is about THIS process's migration work — the view
                # may still be pending another process's finalize).
                journal("fin", epoch, 0)
            # Only the process that ORIGINATED the pending transition
            # finalizes it: a gossip adopter's empty/partial catalog
            # draining proves nothing about the originator's migration,
            # and its view settles when the finalized epoch gossips back
            # (docs/membership.md, gossip section).
            if not membership.owns_transition and not membership.settled:
                return debt
            # Guarded: only the epoch this pass PLANNED at may finalize —
            # a transition that landed after the plan (even against an
            # empty task list) must be re-planned, never rubber-stamped.
            if membership.finalize_transitions(expected_epoch=epoch) is None:
                if membership.view().epoch != epoch:
                    self._c["reshard_replans"] += 1
                    with self._cv:
                        self._dirty = True
                    return debt
            # A drained pass supersedes its incremental records whether or
            # not a finalize was pending (a mark_dead re-replication drains
            # with the view ALREADY settled): compact so restarts replay a
            # bounded snapshot with the final holder sets.
            if tasks:
                compact = getattr(self.cluster, "compact_journal", None)
                if compact is not None:
                    compact()
            # Finalizing bumps the epoch but creates no new delta (JOINING
            # and ACTIVE place identically; LEAVING was already out) — the
            # catalog may still have grown, so one more plan() confirms.
            # Only COPY work re-arms the pass: prune debt (a stale copy
            # behind an OPEN breaker) is retried on later kicks instead of
            # hot-looping against a member that fast-fails every delete.
            if any(t.targets for t in self.cluster.reshard_plan()):
                with self._cv:
                    self._dirty = True
        self._c["reshard_last_pass_ms"] = round(
            (self._clock() - t0) * 1e3, 3
        )
        return debt

    # -- one root ------------------------------------------------------------

    def _migrate_root(self, task: _RootTask) -> Tuple[bool, int]:
        """Copy ``task.root``'s keys to every missing placement member,
        then prune the copies rendezvous no longer wants. Returns
        ``(copies_ok, prune_failures)``: copy failures are hard debt (the
        pass retries until every target holds a copy); failed prunes stay
        in the catalog so later plans retry them (a moved root never
        silently accretes copies) WITHOUT blocking convergence — a stale
        copy is pool space, not a correctness or availability hole.

        Prune safety: prunes run only when every copy landed skip-free
        ("gone" — the root was dropped mid-copy — and skipped-key copies
        both suppress them), so a complete old copy is never deleted in
        favor of one with eviction holes; the plan's ``want_floor`` check
        provides the same guarantee for prune-only retries."""
        ok = True
        gone = False
        skipped_before = self._c["reshard_skipped_keys"]
        for dst in task.targets:
            status = self._copy_root(task, dst)
            if status == "gone":
                gone = True
            elif status != "ok":
                ok = False
        prune_failures = 0
        if ok and not gone and self._c["reshard_skipped_keys"] == skipped_before:
            for mid in task.prune:
                if not self._prune_copy(task, mid):
                    prune_failures += 1
        return ok, prune_failures

    def _copy_root(self, task: _RootTask, dst_id: str) -> str:
        """Copy one root from the first serving holder to ``dst_id``,
        BACKGROUND-tagged, through both members' breakers. Transport
        errors feed the breaker of the SIDE that failed (``_CopyError``):
        a dying source must not open a healthy destination's circuit.

        Returns ``"ok"`` (copied; a skip-free copy recorded ``dst_id`` as
        a level-``task.blocks`` holder; one with eviction holes recorded
        level 0 so it can never justify a prune or serve as a source),
        ``"gone"`` (the root's record vanished mid-copy — dropped — and
        the stray copy was undone), or ``"failed"`` (debt; retried)."""
        cluster = self.cluster
        try:
            di = cluster.member_index(dst_id)
        except KeyError:
            return "failed"
        for src_id in task.sources:
            try:
                si = cluster.member_index(src_id)
            except KeyError:
                continue
            if cluster._begin(si) is None:
                continue  # breaker OPEN: fast-fail this source locally
            try:
                groups = cluster.members[si].manifest(task.tokens, task.blocks)
            except InfiniStoreException as e:
                cluster._done(si, e)
                continue
            except BaseException:
                # Non-store failure (e.g. a duck-typed member without
                # manifest): the breaker must still see an outcome or a
                # half-open probe wedges HALF_OPEN forever (same
                # discipline as the cluster's read paths).
                cluster._done(si, None)
                raise
            if cluster._begin(di) is None:
                cluster._done(si, None)
                return "failed"  # destination breaker OPEN: leave as debt
            try:
                moved_keys, moved_bytes, skipped = self._copy_groups(
                    cluster.members[si], cluster.members[di], groups
                )
            except _CopyError as e:
                if e.side == "src":
                    # The source's transport failed mid-read: feed ITS
                    # breaker, settle the destination as answered, and try
                    # the next holder.
                    cluster._done(si, e.cause)
                    cluster._done(di, None)
                    continue
                # The destination failed the write: feed its breaker; the
                # source answered fine.
                cluster._done(si, None)
                cluster._done(di, e.cause)
                return "failed"
            except BaseException:
                # Non-store failure: both breakers must still see an
                # outcome or a half-open probe wedges HALF_OPEN forever.
                cluster._done(si, None)
                cluster._done(di, None)
                raise
            cluster._done(si, None)
            cluster._done(di, None)
            self._c["reshard_moved_keys"] += moved_keys
            self._c["reshard_moved_bytes"] += moved_bytes
            self._c["reshard_skipped_keys"] += skipped
            level = task.blocks if skipped == 0 else 0
            if skipped:
                # The source's copy proved incomplete at its claimed level
                # (keys evicted under the read): demote it so the next
                # pass re-sources from a complete holder — or, if none is
                # left, stops planning this root instead of retrying the
                # same holes forever.
                cluster.catalog_demote_holder(task.root, src_id)
            if not cluster.catalog_add_holder(task.root, dst_id, level):
                # The root was dropped while this copy was in flight: the
                # delete already swept every cataloged holder, so the copy
                # that just landed is the ONLY stray — undo it, or the new
                # owner would serve a dropped prompt forever (no later
                # plan can prune a root the catalog no longer knows).
                try:
                    for _, keys in groups:
                        cluster.members[di].conn.delete_keys(keys)
                except InfiniStoreException as e:
                    cluster._done(di, e)
                return "gone"
            return "ok"
        return "failed"

    def _copy_groups(self, src, dst, groups) -> Tuple[int, int, int]:
        """Move every (block_size, keys) manifest group src -> dst in
        bounded BACKGROUND batches through a transfer-scoped registered
        staging buffer. Returns (keys moved, bytes moved, keys skipped —
        evicted under the copy)."""
        moved = nbytes = skipped = 0
        for size, keys in groups:
            per = max(1, self.max_batch_bytes // max(1, size))
            for s in range(0, len(keys), per):
                chunk = keys[s : s + per]
                m, b, sk = self._copy_chunk(src.conn, dst.conn, chunk, size)
                moved += m
                nbytes += b
                skipped += sk
        return moved, nbytes, skipped

    def _copy_chunk(self, src_conn, dst_conn, keys: List[str],
                    size: int) -> Tuple[int, int, int]:
        buf = np.empty(len(keys) * size, dtype=np.uint8)
        blocks = [(k, i * size) for i, k in enumerate(keys)]
        try:
            src_conn.register_mr(buf)
            try:
                # Migration reads are BACKGROUND by contract (ITS-P003):
                # they must never delay a decode-blocking foreground read.
                src_conn.read_cache(
                    blocks, size, buf.ctypes.data,
                    priority=PRIORITY_BACKGROUND,
                )
            finally:
                self._unregister(src_conn, buf)
        except (InfiniStoreKeyNotFound, InfiniStoreResourcePressure):
            # Some key raced eviction (or sits spilled behind a pressured
            # pool) between plan and copy: the batch is all-or-nothing, so
            # fall back per key and skip the unreadable ones — a shorter
            # prefix on the destination is legal (prefix match just hits
            # less); fabricating bytes would not be. Treating pressure as
            # debt instead would wedge the reshard for as long as the
            # source stays full.
            return self._copy_chunk_slow(src_conn, dst_conn, keys)
        except InfiniStoreException as e:
            raise _CopyError("src", e)  # the caller feeds the src breaker
        try:
            dst_conn.register_mr(buf)
            try:
                dst_conn.write_cache(
                    blocks, size, buf.ctypes.data,
                    priority=PRIORITY_BACKGROUND,
                )
            finally:
                self._unregister(dst_conn, buf)
        except InfiniStoreException as e:
            raise _CopyError("dst", e)  # the caller feeds the dst breaker
        return len(keys), len(keys) * size, 0

    def _copy_chunk_slow(self, src_conn, dst_conn,
                         keys: List[str]) -> Tuple[int, int, int]:
        """Per-key fallback when a batched copy hit an evicted or
        pressured key. Reads ride the single-key TCP path (the one op that
        can answer per-key instead of all-or-nothing; its priority tag is
        a client-side no-op — acceptable for this rare eviction-race
        path); the WRITES, where migration load would contend with the
        destination's foreground service, go through a single-key batched
        op so the BACKGROUND tag is real on the wire (ITS-P003,
        docs/qos.md)."""
        moved = nbytes = skipped = 0
        for key in keys:
            try:
                data = src_conn.tcp_read_cache(
                    key, priority=PRIORITY_BACKGROUND
                )
            except (InfiniStoreKeyNotFound, InfiniStoreResourcePressure):
                skipped += 1  # evicted/pressured away: skip, never fabricate
                continue
            except InfiniStoreException as e:
                raise _CopyError("src", e)
            arr = np.ascontiguousarray(data)
            try:
                dst_conn.register_mr(arr)
                try:
                    dst_conn.write_cache(
                        [(key, 0)], arr.nbytes, arr.ctypes.data,
                        priority=PRIORITY_BACKGROUND,
                    )
                finally:
                    self._unregister(dst_conn, arr)
            except InfiniStoreException as e:
                raise _CopyError("dst", e)
            moved += 1
            nbytes += arr.nbytes
        return moved, nbytes, skipped

    def _prune_copy(self, task: _RootTask, member_id: str) -> bool:
        """Delete a copy rendezvous no longer places on ``member_id`` (the
        *move* half of a join's delta transfer). A failed prune costs pool
        bytes, not correctness — errors feed the breaker and the task
        stays in the plan (prune debt is retried until it drains or the
        member stops being ACTIVE). Returns True when the prune landed."""
        cluster = self.cluster
        try:
            i = cluster.member_index(member_id)
        except KeyError:
            return True  # entry gone: nothing left to prune
        if cluster._begin(i) is None:
            return False
        try:
            groups = cluster.members[i].manifest(task.tokens, task.blocks)
            n = 0
            for _, keys in groups:
                n += cluster.members[i].conn.delete_keys(keys)
            self._c["reshard_pruned_keys"] += n
        except InfiniStoreException as e:
            cluster._done(i, e)
            return False
        except BaseException:
            cluster._done(i, None)  # never wedge a probe
            raise
        cluster._done(i, None)
        cluster.catalog_remove_holder(task.root, member_id)
        return True

    @staticmethod
    def _unregister(conn, buf):
        try:
            conn.unregister_mr(buf)
        # Audited: transfer-scoped MR teardown on a possibly-severed
        # transport — the data-plane error (if any) already routed through
        # _done in the caller; a failed unregister leaves nothing live.
        except InfiniStoreException:  # its: allow[ITS-P001]
            pass
