"""Packaging: builds the native core via make (the reference shells out to
meson+ninja the same way, reference setup.py:30-50) and ships the .so
inside the wheel. Console entry point mirrors the reference's `infinistore`
script (setup.py:74-78)."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))


class BuildNative(build_py):
    def run(self):
        native = os.path.join(HERE, "native")
        so = os.path.join(
            HERE, "infinistore_tpu", "_native", "libinfinistore_tpu.so"
        )
        if os.path.isdir(native):
            subprocess.run(
                ["make", "-j", str(os.cpu_count() or 2)], cwd=native, check=True
            )
        elif not os.path.exists(so):
            raise RuntimeError(
                "native/ sources missing and no prebuilt libinfinistore_tpu.so; "
                "the sdist must include native/** (see MANIFEST.in)"
            )
        super().run()


setup(
    name="infinistore-tpu",
    version="0.1.0",
    description="TPU-native distributed KV-cache store for LLM inference clusters",
    packages=[
        "infinistore_tpu",
        "infinistore_tpu._native",
        "infinistore_tpu.tpu",
        "infinistore_tpu.models",
    ],
    package_data={"infinistore_tpu._native": ["libinfinistore_tpu.so"]},
    include_package_data=True,
    python_requires=">=3.10",
    install_requires=["numpy"],
    extras_require={"tpu": ["jax"]},
    cmdclass={"build_py": BuildNative},
    entry_points={
        "console_scripts": [
            "infinistore-tpu = infinistore_tpu.server:main",
            "infinistore-tpu-benchmark = infinistore_tpu.benchmark:main",
        ]
    },
)
