"""Packaging: builds the native core via make (the reference shells out to
meson+ninja the same way, reference setup.py:30-50) and ships the .so
inside the wheel. Console entry point mirrors the reference's `infinistore`
script (setup.py:74-78).

Wheel tagging: the native core is reached through ctypes, not a CPython
extension module, so ONE ``py3-none-<platform>`` wheel serves every CPython
>= 3.10 — where the reference must build a cp310/cp311/cp312 manylinux
matrix (reference build_manylinux_wheels.sh:1-22), we ship a single
platform wheel. The .so links only glibc/libstdc++ (no ibverbs analogue to
exclude); tools/build_wheel.sh runs the auditwheel policy check and the
fresh-venv install + smoke test."""

import os
import subprocess

from setuptools import setup
from setuptools.command.bdist_wheel import bdist_wheel
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

HERE = os.path.dirname(os.path.abspath(__file__))


class BuildNative(build_py):
    def run(self):
        native = os.path.join(HERE, "native")
        so = os.path.join(
            HERE, "infinistore_tpu", "_native", "libinfinistore_tpu.so"
        )
        if os.path.isdir(native):
            subprocess.run(
                ["make", "-j", str(os.cpu_count() or 2)], cwd=native, check=True
            )
        elif not os.path.exists(so):
            raise RuntimeError(
                "native/ sources missing and no prebuilt libinfinistore_tpu.so; "
                "the sdist must include native/** (see MANIFEST.in)"
            )
        super().run()


class BinaryDistribution(Distribution):
    """Force the platlib install layout: the package bundles a native .so,
    so the wheel root must be platlib (auditwheel rejects shared libraries
    under a purelib root)."""

    def has_ext_modules(self):
        return True


class PlatformWheel(bdist_wheel):
    """Tag the wheel py3-none-<plat>: platform-specific (bundled .so) but
    CPython-version-independent (ctypes FFI, no extension ABI)."""

    def get_tag(self):
        _, _, plat = super().get_tag()
        return "py3", "none", plat


setup(
    name="infinistore-tpu",
    version="0.1.0",
    description="TPU-native distributed KV-cache store for LLM inference clusters",
    packages=[
        "infinistore_tpu",
        "infinistore_tpu._native",
        "infinistore_tpu.tpu",
        "infinistore_tpu.models",
    ],
    package_data={"infinistore_tpu._native": ["libinfinistore_tpu.so"]},
    include_package_data=True,
    python_requires=">=3.10",
    install_requires=["numpy"],
    extras_require={"tpu": ["jax"]},
    distclass=BinaryDistribution,
    cmdclass={"build_py": BuildNative, "bdist_wheel": PlatformWheel},
    entry_points={
        "console_scripts": [
            "infinistore-tpu = infinistore_tpu.server:main",
            "infinistore-tpu-benchmark = infinistore_tpu.benchmark:main",
        ]
    },
)
