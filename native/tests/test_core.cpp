// Native-level unit tests for the allocator, KV/LRU store, wire codec, and a
// full in-process client<->server loopback pass. The reference ships zero C++
// tests (SURVEY.md §4 calls its hardware-gated test strategy the weakest
// subsystem); this binary runs in CI under ASAN too (`make check-asan`), which
// the Python/ctypes suite cannot do.
//
// Deliberately dependency-free (no gtest in the image): tiny CHECK macro,
// main() runs every case, nonzero exit on failure.
#include <fcntl.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <string>
#include <thread>
#include <vector>

#include "its/client.h"
#include "its/kvstore.h"
#include "its/log.h"
#include "its/mempool.h"
#include "its/protocol.h"
#include "its/ring.h"
#include "its/server.h"

static std::atomic<int> g_failures{0};

#define CHECK(cond)                                                            \
    do {                                                                       \
        if (!(cond)) {                                                         \
            fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);    \
            g_failures++;                                                      \
        }                                                                      \
    } while (0)

using namespace its;

static void test_mempool_basic() {
    MemoryPool pool(1 << 20, 4 << 10, /*pin=*/false);
    CHECK(pool.total_blocks() == 256);
    void* a = pool.allocate(4 << 10);
    void* b = pool.allocate(12 << 10);  // 3 contiguous blocks
    CHECK(a != nullptr && b != nullptr && a != b);
    CHECK(pool.used_blocks() == 4);
    CHECK(pool.deallocate(a, 4 << 10));
    CHECK(!pool.deallocate(a, 4 << 10));  // double free detected
    char foreign[64];
    CHECK(!pool.deallocate(foreign, 64));  // foreign pointer rejected
    CHECK(pool.deallocate(b, 12 << 10));
    CHECK(pool.used_blocks() == 0);
}

static void test_mempool_exhaustion_and_rollback() {
    MM mm(64 << 10, 16 << 10, false);  // 4 blocks
    std::vector<Lease> leases;
    CHECK(mm.allocate(16 << 10, 3, nullptr, &leases));
    std::vector<Lease> more;
    // 2 more can't fit: all-or-nothing must roll back, freeing nothing held.
    CHECK(!mm.allocate(16 << 10, 2, nullptr, &more));
    CHECK(more.empty());
    CHECK(mm.used_bytes() == 3 * (16 << 10));
    for (const auto& l : leases) mm.deallocate(l);
    CHECK(mm.used_bytes() == 0);
    // Extend adds capacity.
    CHECK(mm.extend(64 << 10));
    std::vector<Lease> big;
    CHECK(mm.allocate(16 << 10, 7, nullptr, &big));
    for (const auto& l : big) mm.deallocate(l);
}

static void test_kvstore_lru_eviction() {
    MM mm(64 << 10, 16 << 10, false);  // 4 blocks
    KVStore kv(&mm);
    auto put = [&](const std::string& key) {
        std::vector<Lease> l;
        if (!mm.allocate(16 << 10, 1, nullptr, &l)) return false;
        kv.commit(key, std::make_shared<Block>(&mm, l[0].ptr, l[0].size));
        return true;
    };
    CHECK(put("a") && put("b") && put("c") && put("d"));
    CHECK(kv.size() == 4);
    CHECK(kv.get("a") != nullptr);  // touch "a": now most-recent
    // Pool full (usage 1.0 >= max 0.9): evict to min 0.5 -> 2 evictions,
    // oldest-first means "b" and "c" go, "a" stays.
    size_t evicted = kv.evict(0.5, 0.9);
    CHECK(evicted == 2);
    CHECK(kv.exists("a"));
    CHECK(!kv.exists("b"));
    CHECK(!kv.exists("c"));
    CHECK(kv.exists("d"));
    // match_last_index under the prefix property.
    std::vector<std::string> chain = {"a", "d", "zz"};
    CHECK(kv.match_last_index(chain) == 1);
    CHECK(kv.match_last_index({"nope"}) == -1);
    CHECK(kv.purge() == 2);
    CHECK(mm.used_bytes() == 0);  // refcount returned every block
}

static void test_kvstore_overwrite_slot() {
    MM mm(64 << 10, 16 << 10, false);  // 4 blocks
    KVStore kv(&mm);
    auto put = [&](const std::string& key) {
        std::vector<Lease> l;
        CHECK(mm.allocate(16 << 10, 1, nullptr, &l));
        kv.commit(key, std::make_shared<Block>(&mm, l[0].ptr, l[0].size));
    };
    put("a");
    put("b");
    // Resident, size-matched, only-reference: eligible, and the fast path
    // hands back the committed block itself (copy lands in place).
    CHECK(kv.overwrite_eligible("a", 16 << 10));
    BlockRef slot = kv.overwrite_slot("a", 16 << 10);
    CHECK(slot != nullptr && slot == kv.get("a"));
    // overwrite_slot touched "a": with the pool full, a one-entry evict
    // (4 -> 3 blocks = 0.75 usage <= 0.8) must take the colder "b".
    slot.reset();
    put("c");
    put("d");
    CHECK(kv.evict(0.8, 0.9) == 1);
    CHECK(kv.exists("a") && !kv.exists("b"));
    // Size mismatch and missing key: ineligible, no slot.
    CHECK(!kv.overwrite_eligible("a", 8 << 10));
    CHECK(kv.overwrite_slot("a", 8 << 10) == nullptr);
    CHECK(!kv.overwrite_eligible("nope", 16 << 10));
    // A pinned reader (outstanding BlockRef) blocks the in-place path —
    // mutating the block would tear that reader's snapshot.
    BlockRef pinned = kv.get("a");
    CHECK(!kv.overwrite_eligible("a", 16 << 10));
    CHECK(kv.overwrite_slot("a", 16 << 10) == nullptr);
    pinned.reset();
    CHECK(kv.overwrite_eligible("a", 16 << 10));
    kv.purge();
    CHECK(mm.used_bytes() == 0);
}

static void test_wire_codec_roundtrip() {
    BatchMeta m;
    m.block_size = 4096;
    m.keys = {"k1", "", std::string(300, 'x')};
    std::vector<uint8_t> buf;
    m.encode(buf);
    BatchMeta d = BatchMeta::decode(buf.data(), buf.size());
    CHECK(d.block_size == 4096 && d.keys == m.keys);

    ShmLocResp r;
    r.ticket = 0xdeadbeefcafe;
    r.locs = {{1, 65536, 4096}, {0, 0, 1}};
    r.pools = {{0, "/its.1.2.0", 1 << 20}};
    buf.clear();
    r.encode(buf);
    ShmLocResp rd = ShmLocResp::decode(buf.data(), buf.size());
    CHECK(rd.ticket == r.ticket && rd.locs.size() == 2 && rd.pools.size() == 1);
    CHECK(rd.locs[0].offset == 65536 && rd.pools[0].name == "/its.1.2.0");

    // Truncated body must throw, not read OOB (ASAN-visible if it did).
    bool threw = false;
    try {
        BatchMeta::decode(buf.data(), 3);
    } catch (const std::exception&) {
        threw = true;
    }
    CHECK(threw);
}

static void test_loopback_end_to_end(bool enable_shm) {
    ServerConfig scfg;
    scfg.bind_addr = "127.0.0.1";
    scfg.service_port = 0;
    scfg.prealloc_bytes = 16 << 20;
    scfg.block_size = 16 << 10;
    scfg.pin_memory = false;
    scfg.enable_shm = enable_shm;
    Server server(scfg);
    CHECK(server.start());

    ClientConfig ccfg;
    ccfg.host = "127.0.0.1";
    ccfg.port = server.port();
    ccfg.enable_shm = enable_shm;
    Connection conn(ccfg);
    CHECK(conn.connect() == 0);
    CHECK(conn.shm_active() == enable_shm);

    const size_t n = 8, bs = 16 << 10;
    std::vector<char> src(n * bs), dst(n * bs, 0);
    for (size_t i = 0; i < src.size(); i++) src[i] = static_cast<char>(i * 31 + 7);
    conn.register_mr(src.data(), src.size());
    conn.register_mr(dst.data(), dst.size());

    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("blk" + std::to_string(i));
        offs.push_back(i * bs);
    }
    std::atomic<int> code{-1};
    auto cb = [](void* ctx, int c) { static_cast<std::atomic<int>*>(ctx)->store(c); };
    CHECK(conn.put_batch_async(keys, offs, bs, src.data(), cb, &code) == 0);
    for (int i = 0; i < 500 && code.load() == -1; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(code.load() == 200);

    code.store(-1);
    CHECK(conn.get_batch_async(keys, offs, bs, dst.data(), cb, &code) == 0);
    for (int i = 0; i < 500 && code.load() == -1; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(code.load() == 200);
    CHECK(memcmp(src.data(), dst.data(), src.size()) == 0);

    // Control ops.
    CHECK(conn.check_exist("blk0") == 1);
    CHECK(conn.check_exist("nope") == 0);
    CHECK(conn.get_match_last_index({"blk0", "blk1", "missing"}) == 1);
    // TCP single-key path + typed miss.
    CHECK(conn.tcp_put("tk", src.data(), 1024) == 0);
    uint8_t* out = nullptr;
    size_t out_size = 0;
    CHECK(conn.tcp_get("tk", &out, &out_size) == 0);
    CHECK(out_size == 1024 && memcmp(out, src.data(), 1024) == 0);
    free(out);
    CHECK(conn.tcp_get("missing", &out, &out_size) == -404);
    CHECK(conn.delete_keys({"blk0", "tk", "ghost"}) == 2);
    CHECK(server.kvmap_len() == n - 1);

    conn.close();
    server.stop();
}

static void test_spill_tier_demote_promote() {
    // KVStore + SpillFile: evict demotes to the file, get promotes back,
    // bytes survive the round trip, slots are freed on delete/overwrite,
    // and a full spill file drops only the coldest entries.
    MM mm(8 * 64 << 10, 64 << 10, /*pin=*/false);  // 8 blocks of RAM
    SpillFile spill("/tmp", 32 * 64 << 10, 64 << 10);
    CHECK(spill.ok());
    KVStore kv(&mm, &spill);

    auto put = [&](const std::string& key, char fill) {
        std::vector<Lease> leases;
        CHECK(mm.allocate(64 << 10, 1, [](void*, size_t) {}, &leases));
        memset(leases[0].ptr, fill, 64 << 10);
        kv.commit(key, std::make_shared<Block>(&mm, leases[0].ptr, 64 << 10));
    };

    for (int i = 0; i < 24; i++) {
        kv.evict(0.5, 0.9);  // the server's on-demand pattern
        put("k" + std::to_string(i), static_cast<char>('a' + i));
    }
    CHECK(kv.size() == 24);               // nothing lost: 8 RAM + 16 spilled
    CHECK(kv.spilled_entries() >= 16);
    CHECK(kv.spill_drops() == 0);

    // Promote an old (spilled) entry; its bytes must be intact.
    BlockRef b = kv.get("k0");
    CHECK(b != nullptr);
    CHECK(static_cast<char*>(b->data())[0] == 'a');
    CHECK(static_cast<char*>(b->data())[(64 << 10) - 1] == 'a');
    CHECK(kv.spill_promotions() == 1);

    // Control ops: spilled entries are present without promotion.
    uint64_t promos = kv.spill_promotions();
    CHECK(kv.exists("k1"));
    std::vector<std::string> chain;
    for (int i = 0; i < 24; i++) chain.push_back("k" + std::to_string(i));
    CHECK(kv.match_last_index(chain) == 23);
    CHECK(kv.spill_promotions() == promos);

    // Delete frees spill slots.
    size_t bytes_before = kv.spilled_bytes();
    CHECK(bytes_before > 0);
    CHECK(kv.remove({"k1", "k2"}) == 2);
    CHECK(kv.spilled_bytes() < bytes_before);

    // Fill far beyond RAM+spill: the coldest spilled entries drop, the
    // newest stay readable.
    for (int i = 100; i < 200; i++) {
        kv.evict(0.5, 0.9);
        put("z" + std::to_string(i), static_cast<char>(i));
    }
    CHECK(kv.spill_drops() > 0);
    BlockRef newest = kv.get("z199");
    CHECK(newest != nullptr);
    CHECK(static_cast<char*>(newest->data())[7] == static_cast<char>(199));
    kv.purge();
    CHECK(kv.spilled_bytes() == 0);
}

static void test_abandoned_sync_ops_stress(bool enable_shm) {
    // The documented timeout contract: after a sync op raises, the caller
    // may unregister and FREE the buffer — the reactor must never touch it
    // again (SyncState::abandoned + io_seq_ Dekker pairing, client.cpp).
    // Regime: 16MB ops (several ms of streaming/memcpy) against a 1ms
    // deadline, so ops are abandoned unsent, mid-stream, mid-scatter, and
    // awaiting a late response. Each iteration frees its buffer immediately
    // — under ASAN/TSAN any late reactor touch is a hard failure. A
    // mid-stream put abandonment intentionally fails the connection; the
    // loop reconnects, covering that path too.
    ServerConfig scfg;
    scfg.bind_addr = "127.0.0.1";
    scfg.service_port = 0;
    scfg.prealloc_bytes = 256 << 20;
    scfg.block_size = 64 << 10;
    scfg.pin_memory = false;
    scfg.enable_shm = enable_shm;
    Server server(scfg);
    CHECK(server.start());

    const size_t n = 64, bs = 256 << 10;  // 16MB per op
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("ab" + std::to_string(i));
        offs.push_back(i * bs);
    }

    // Seed the keys with a patient connection so gets have data to return.
    {
        ClientConfig seed_cfg;
        seed_cfg.host = "127.0.0.1";
        seed_cfg.port = server.port();
        seed_cfg.enable_shm = enable_shm;
        Connection seed(seed_cfg);
        CHECK(seed.connect() == 0);
        std::vector<char> src(n * bs, 'S');
        seed.register_mr(src.data(), src.size());
        CHECK(seed.put_batch(keys, offs, bs, src.data()) == 0);
        seed.close();
    }

    ClientConfig ccfg;
    ccfg.host = "127.0.0.1";
    ccfg.port = server.port();
    ccfg.enable_shm = enable_shm;
    ccfg.op_timeout_ms = 1;
    auto conn = std::make_unique<Connection>(ccfg);
    CHECK(conn->connect() == 0);

    int fails = 0, oks = 0, reconnects = 0;
    for (int it = 0; it < 40; it++) {
        auto buf = std::make_unique<std::vector<char>>(n * bs,
                                                       static_cast<char>(it));
        conn->register_mr(buf->data(), buf->size());
        int rc = (it & 1) ? conn->get_batch(keys, offs, bs, buf->data())
                          : conn->put_batch(keys, offs, bs, buf->data());
        rc == 0 ? oks++ : fails++;
        // The documented sequence after a timeout: unregister, scribble,
        // free. If the reactor still holds an iovec into this memory, the
        // sanitizers see the touch after the delete below.
        conn->unregister_mr(buf->data());
        memset(buf->data(), 0xDD, 4096);
        buf.reset();
        if (rc != 0) {
            // Mid-stream abandonment fails the connection by design; a
            // fresh connection also covers connect/teardown under churn.
            conn->close();
            conn = std::make_unique<Connection>(ccfg);
            CHECK(conn->connect() == 0);
            reconnects++;
        }
    }
    // Let any last late responses land (and be drained) while the final
    // connection is still alive.
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
    CHECK(fails > 0);  // the abandoned regime was actually exercised
    conn->close();
    server.stop();
    (void)oks;
    (void)reconnects;
}

// Eventfd completion ring: concurrent pushes from the reactor against a
// draining "event loop" thread, fd signalling semantics, and fail-all
// delivery through the ring. Runs under ASAN and TSAN in CI — this is the
// cross-thread structure the Python asyncio bridge relies on.
static void test_completion_ring(bool enable_shm) {
    ServerConfig scfg;
    scfg.bind_addr = "127.0.0.1";
    scfg.service_port = 0;
    scfg.prealloc_bytes = 16 << 20;
    scfg.block_size = 16 << 10;
    scfg.pin_memory = false;
    scfg.enable_shm = enable_shm;
    Server server(scfg);
    CHECK(server.start());

    ClientConfig ccfg;
    ccfg.host = "127.0.0.1";
    ccfg.port = server.port();
    ccfg.enable_shm = enable_shm;
    Connection conn(ccfg);
    CHECK(conn.connect() == 0);

    int efd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    CHECK(efd >= 0);
    conn.set_completion_fd(efd);

    const size_t n = 4, bs = 16 << 10;
    std::vector<char> src(n * bs);
    for (size_t i = 0; i < src.size(); i++) src[i] = static_cast<char>(i * 13 + 3);
    conn.register_mr(src.data(), src.size());
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("ring" + std::to_string(i));
        offs.push_back(i * bs);
    }

    // Drainer thread = the event loop: waits on the fd, drains tokens.
    const int kOps = 200;
    std::atomic<bool> stop{false};
    std::atomic<int> drained{0};
    std::atomic<int> ok_codes{0};
    std::thread drainer([&] {
        uint64_t tokens[32];
        int32_t codes[32];
        while (!stop.load()) {
            uint64_t v;
            if (read(efd, &v, sizeof(v)) < 0)
                std::this_thread::sleep_for(std::chrono::microseconds(200));
            int got;
            while ((got = conn.drain_completions(tokens, codes, 32)) > 0) {
                for (int i = 0; i < got; i++) {
                    drained.fetch_add(1);
                    if (codes[i] == 200) ok_codes.fetch_add(1);
                    CHECK(tokens[i] >= 1 && tokens[i] <= kOps);
                }
            }
        }
    });

    // Ring-mode submits: cb = nullptr, ctx = token.
    for (int i = 1; i <= kOps; i++) {
        CHECK(conn.put_batch_async(keys, offs, bs, src.data(), nullptr,
                                   reinterpret_cast<void*>(static_cast<uintptr_t>(i))) == 0);
        if (i % 16 == 0) {
            // Throttle so the in-flight window stays modest.
            for (int spin = 0; spin < 2000 && drained.load() < i - 32; spin++)
                std::this_thread::sleep_for(std::chrono::milliseconds(1));
        }
    }
    for (int spin = 0; spin < 5000 && drained.load() < kOps; spin++)
        std::this_thread::sleep_for(std::chrono::milliseconds(1));
    CHECK(drained.load() == kOps);
    CHECK(ok_codes.load() == kOps);

    // fail_all delivery: submit, then close the connection — every pending
    // op must surface through the ring with a non-200 code (or have
    // completed 200 first), never vanish.
    int before = drained.load();
    int accepted = 0;
    for (int i = 1; i <= 8; i++) {
        if (conn.put_batch_async(keys, offs, bs, src.data(), nullptr,
                                 reinterpret_cast<void*>(static_cast<uintptr_t>(i))) == 0)
            accepted++;
    }
    CHECK(accepted == 8);  // a rejected submit never enters the ring
    conn.close();  // reactor joined: completions (success or 503) are in the ring
    uint64_t tokens[32];
    int32_t codes[32];
    int got, total_after = 0;
    while ((got = conn.drain_completions(tokens, codes, 32)) > 0) total_after += got;
    // Drainer may have consumed some first; between both, all 8 resolved.
    stop.store(true);
    drainer.join();
    int resolved = drained.load() - before + total_after;
    CHECK(resolved == accepted);

    close(efd);
    server.stop();
}

static void test_qos_wire_priority_tag() {
    // The QoS class tag is an OPTIONAL trailing byte: an untagged
    // (foreground) body must be byte-identical to the pre-QoS encoding,
    // and a tagged body is that encoding plus exactly one byte.
    BatchMeta m;
    m.block_size = 4096;
    m.keys = {"a", "b"};
    std::vector<uint8_t> untagged;
    m.encode(untagged);
    m.priority = kPriorityBackground;
    std::vector<uint8_t> tagged;
    m.encode(tagged);
    CHECK(tagged.size() == untagged.size() + 1);
    CHECK(memcmp(tagged.data(), untagged.data(), untagged.size()) == 0);
    CHECK(tagged.back() == kPriorityBackground);
    CHECK(BatchMeta::decode(untagged.data(), untagged.size()).priority ==
          kPriorityForeground);
    CHECK(BatchMeta::decode(tagged.data(), tagged.size()).priority ==
          kPriorityBackground);

    SegBatchMeta sm;
    sm.block_size = 4096;
    sm.seg_id = 3;
    sm.keys = {"k"};
    sm.offsets = {65536};
    std::vector<uint8_t> s0;
    sm.encode(s0);
    sm.priority = kPriorityBackground;
    std::vector<uint8_t> s1;
    sm.encode(s1);
    CHECK(s1.size() == s0.size() + 1 && s1.back() == kPriorityBackground);
    CHECK(SegBatchMeta::decode(s0.data(), s0.size()).priority ==
          kPriorityForeground);
    SegBatchMeta sd = SegBatchMeta::decode(s1.data(), s1.size());
    CHECK(sd.priority == kPriorityBackground && sd.offsets == sm.offsets);
}

static long long stat_counter(const std::string& json, const char* key) {
    std::string needle = std::string("\"") + key + "\":";
    size_t at = json.find(needle);
    if (at == std::string::npos) return -1;
    return atoll(json.c_str() + at + needle.size());
}

static void test_trace_wire_context() {
    // The trace context is a SECOND trailing optional extension after the
    // QoS byte (docs/observability.md): untraced stays byte-identical to
    // the pre-trace encoding; a traced FOREGROUND op gains exactly the
    // priority byte + 16 trace bytes (the priority byte must be forced so
    // the trailing-optional decode walk stays unambiguous).
    BatchMeta m;
    m.block_size = 4096;
    m.keys = {"a", "b"};
    std::vector<uint8_t> plain;
    m.encode(plain);
    m.trace_id = 0x1122334455667788ull;
    m.trace_parent = 0x99aabbccddeeff00ull;
    std::vector<uint8_t> traced;
    m.encode(traced);
    CHECK(traced.size() == plain.size() + 1 + 16);
    CHECK(memcmp(traced.data(), plain.data(), plain.size()) == 0);
    CHECK(traced[plain.size()] == kPriorityForeground);
    BatchMeta d = BatchMeta::decode(traced.data(), traced.size());
    CHECK(d.trace_id == m.trace_id && d.trace_parent == m.trace_parent);
    CHECK(d.priority == kPriorityForeground);
    CHECK(BatchMeta::decode(plain.data(), plain.size()).trace_id ==
          kTraceIdNone);

    // Background + traced composes: priority byte carries the class.
    SegBatchMeta sm;
    sm.block_size = 4096;
    sm.seg_id = 1;
    sm.keys = {"k"};
    sm.offsets = {0};
    sm.priority = kPriorityBackground;
    sm.trace_id = 42;
    sm.trace_parent = 7;
    std::vector<uint8_t> sb;
    sm.encode(sb);
    SegBatchMeta sd = SegBatchMeta::decode(sb.data(), sb.size());
    CHECK(sd.priority == kPriorityBackground && sd.trace_id == 42 &&
          sd.trace_parent == 7);
}

static void test_trace_ring_loopback(bool enable_shm) {
    // A traced batched op must land one ordered tick record in the
    // server's trace ring (stats_json "trace"), joined by trace id, while
    // untraced ops leave the ring untouched.
    ServerConfig scfg;
    scfg.bind_addr = "127.0.0.1";
    scfg.service_port = 0;
    scfg.prealloc_bytes = 16 << 20;
    scfg.block_size = 16 << 10;
    scfg.pin_memory = false;
    scfg.enable_shm = enable_shm;
    Server server(scfg);
    CHECK(server.start());
    ClientConfig ccfg;
    ccfg.host = "127.0.0.1";
    ccfg.port = server.port();
    ccfg.enable_shm = enable_shm;
    Connection conn(ccfg);
    CHECK(conn.connect() == 0);

    const size_t n = 4, bs = 16 << 10;
    std::vector<char> buf(n * bs, 'x');
    conn.register_mr(buf.data(), buf.size());
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("tr" + std::to_string(i));
        offs.push_back(i * bs);
    }
    // Untraced put: no tick.
    CHECK(conn.put_batch(keys, offs, bs, buf.data()) == 0);
    CHECK(stat_counter(server.stats_json(), "recorded") == 0);
    // Traced get: one tick, ordered, with the op's bytes.
    const uint64_t tid = 0xfeedbeef, span = 0x1234;
    CHECK(conn.get_batch(keys, offs, bs, buf.data(), kPriorityForeground,
                         tid, span) == 0);
    std::string js = server.stats_json();
    CHECK(stat_counter(js, "recorded") == 1);
    CHECK(js.find("\"trace_id\":" + std::to_string(tid)) != std::string::npos);
    CHECK(js.find("\"parent_id\":" + std::to_string(span)) != std::string::npos);
    size_t at = js.find("\"entries\":[{");
    CHECK(at != std::string::npos);
    std::string entry = js.substr(at);
    long long recv = stat_counter(entry, "recv_us");
    long long first = stat_counter(entry, "first_slice_us");
    long long last = stat_counter(entry, "last_slice_us");
    long long done = stat_counter(entry, "done_us");
    CHECK(recv > 0 && recv <= first && first <= last && last <= done);
    CHECK(stat_counter(entry, "bytes") ==
          static_cast<long long>(n * bs));
    conn.close();
    server.stop();
}

static void test_qos_two_level_scheduler() {
    // Reactor-level QoS: a BACKGROUND-tagged batch must (a) complete under
    // a PERMANENT foreground flood — the time-based aging escape makes
    // starvation impossible by construction — (b) be byte-correct despite
    // running entirely from preempted/aged slices, and (c) show up in the
    // scheduler's per-class counters.
    ServerConfig scfg;
    scfg.bind_addr = "127.0.0.1";
    scfg.service_port = 0;
    scfg.prealloc_bytes = 32 << 20;
    scfg.block_size = 16 << 10;
    scfg.pin_memory = false;
    scfg.enable_shm = true;
    Server server(scfg);
    CHECK(server.start());

    ClientConfig ccfg;
    ccfg.host = "127.0.0.1";
    ccfg.port = server.port();
    Connection bg(ccfg), fg(ccfg);
    CHECK(bg.connect() == 0 && fg.connect() == 0);

    const size_t n = 64, bs = 16 << 10;
    std::vector<char> bgbuf(n * bs), rdbuf(n * bs, 0), fgbuf(bs, 'f');
    for (size_t i = 0; i < bgbuf.size(); i++)
        bgbuf[i] = static_cast<char>(i * 13 + 5);
    bg.register_mr(bgbuf.data(), bgbuf.size());
    bg.register_mr(rdbuf.data(), rdbuf.size());
    fg.register_mr(fgbuf.data(), fgbuf.size());
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("bgk" + std::to_string(i));
        offs.push_back(i * bs);
    }
    CHECK(fg.put_batch({"hot"}, {0}, bs, fgbuf.data()) == 0);

    std::atomic<bool> stop{false};
    std::thread flood([&] {
        while (!stop.load())
            fg.get_batch({"hot"}, {0}, bs, fgbuf.data());
    });

    std::atomic<int> code{-1};
    auto cb = [](void* ctx, int c) { static_cast<std::atomic<int>*>(ctx)->store(c); };
    CHECK(bg.put_batch_async(keys, offs, bs, bgbuf.data(), cb, &code,
                             kPriorityBackground) == 0);
    for (int i = 0; i < 2500 && code.load() == -1; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(code.load() == 200);  // completed DURING the flood (aging)
    stop.store(true);
    flood.join();

    // Byte-correctness under preemption: every block survived intact.
    CHECK(bg.get_batch(keys, offs, bs, rdbuf.data(), kPriorityBackground) == 0);
    CHECK(memcmp(bgbuf.data(), rdbuf.data(), bgbuf.size()) == 0);

    std::string st = server.stats_json();
    CHECK(stat_counter(st, "bg_ops") >= 2);  // the tagged put + read-back
    CHECK(stat_counter(st, "fg_ops") >= 2);  // seed put + flood reads
    // The scheduler actually deferred (or aged) background work at least
    // once under the flood — the mechanism ran, not just the bookkeeping.
    CHECK(stat_counter(st, "bg_preempted_slices") +
              stat_counter(st, "bg_aged_slices") > 0);

    bg.close();
    fg.close();
    server.stop();
}

// ---------------------------------------------------------------------------
// Descriptor-ring data plane (docs/descriptor_ring.md). These cases run the
// REAL cross-process protocol in-process (client reactor + server reactor on
// their own threads, the ring header genuinely shared state) — which is
// exactly what check-tsan exists to validate.
// ---------------------------------------------------------------------------

static ClientConfig ring_ccfg(int port, uint32_t ring_slots,
                              bool enable_ring = true) {
    ClientConfig c;
    c.host = "127.0.0.1";
    c.port = port;
    c.enable_ring = enable_ring;
    c.ring_slots = ring_slots;
    return c;
}

static ServerConfig ring_scfg(size_t prealloc = 32 << 20) {
    ServerConfig s;
    s.bind_addr = "127.0.0.1";
    s.service_port = 0;
    s.prealloc_bytes = prealloc;
    s.block_size = 16 << 10;
    s.pin_memory = false;
    s.enable_shm = true;
    return s;
}

static void test_ring_wrap_and_disable() {
    // Cursor wrap: a tiny 4-slot ring must survive many times its depth in
    // sequential ops (seq % slots indexing, head-gated slot reuse), stay
    // byte-correct, and count every descriptor. A ring-disabled connection
    // against the same server must keep working over the socket path with
    // ZERO ring traffic.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/4));
    CHECK(conn.connect() == 0);
    CHECK(conn.shm_active());
    CHECK(conn.ring_active());
    CHECK(!conn.ring_name().empty());

    const size_t n = 4, bs = 16 << 10;
    char* seg = static_cast<char*>(conn.alloc_shm_mr(n * bs));
    CHECK(seg != nullptr);
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < n; i++) {
        keys.push_back("wr" + std::to_string(i));
        offs.push_back(i * bs);
    }
    const int rounds = 10;  // 20 descriptors through 4 slots = 5 wraps
    for (int r = 0; r < rounds; r++) {
        for (size_t i = 0; i < n * bs; i++)
            seg[i] = static_cast<char>(i * 7 + r);
        CHECK(conn.put_batch(keys, offs, bs, seg) == 0);
        memset(seg, 0, n * bs);
        CHECK(conn.get_batch(keys, offs, bs, seg) == 0);
        bool ok = true;
        for (size_t i = 0; i < n * bs && ok; i++)
            ok = seg[i] == static_cast<char>(i * 7 + r);
        CHECK(ok);
    }
    uint64_t posted = 0, doorbells = 0, full = 0, meta = 0, comps = 0;
    conn.ring_counters(&posted, &doorbells, &full, &meta, &comps);
    CHECK(posted == 2 * rounds);
    CHECK(comps == 2 * rounds);
    CHECK(full == 0 && meta == 0);
    std::string st = server.stats_json();
    CHECK(stat_counter(st, "descriptors") == 2 * rounds);
    CHECK(stat_counter(st, "completions") == 2 * rounds);
    CHECK(stat_counter(st, "torn_descriptors") == 0);
    CHECK(stat_counter(st, "attached") == 1);

    // Ring disabled: same ops, socket path, no ring traffic.
    Connection off(ring_ccfg(server.port(), 0, /*enable_ring=*/false));
    CHECK(off.connect() == 0);
    CHECK(off.shm_active());
    CHECK(!off.ring_active());
    CHECK(off.ring_name().empty());
    char* seg2 = static_cast<char*>(off.alloc_shm_mr(bs));
    CHECK(seg2 != nullptr);
    memset(seg2, 'z', bs);
    CHECK(off.put_batch({"offk"}, {0}, bs, seg2) == 0);
    memset(seg2, 0, bs);
    CHECK(off.get_batch({"offk"}, {0}, bs, seg2) == 0);
    CHECK(seg2[0] == 'z' && seg2[bs - 1] == 'z');
    uint64_t p2 = 1;
    off.ring_counters(&p2, nullptr, nullptr, nullptr, nullptr);
    CHECK(p2 == 0);
    CHECK(stat_counter(server.stats_json(), "attached") == 1);  // still just conn's

    off.close();
    conn.close();
    server.stop();
}

static void test_ring_full_backpressure() {
    // A 2-slot ring under a 16-op async burst: the in-flight bound (==
    // cq_slots) forces most ops onto the socket path. Backpressure must be
    // a COUNTED fallback, never an error — every op completes 200 and the
    // bytes land.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/2));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());

    const size_t nops = 16, bs = 16 << 10;
    char* seg = static_cast<char*>(conn.alloc_shm_mr(nops * bs));
    CHECK(seg != nullptr);
    for (size_t i = 0; i < nops * bs; i++) seg[i] = static_cast<char>(i * 11 + 3);
    std::atomic<int> done{0};
    auto cb = [](void* ctx, int c) {
        if (c == 200) static_cast<std::atomic<int>*>(ctx)->fetch_add(1);
    };
    for (size_t i = 0; i < nops; i++)
        CHECK(conn.put_batch_async({"bp" + std::to_string(i)}, {i * bs}, bs, seg,
                                   cb, &done) == 0);
    for (int i = 0; i < 2500 && done.load() < static_cast<int>(nops); i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(done.load() == static_cast<int>(nops));

    uint64_t posted = 0, full = 0, meta = 0, comps = 0;
    conn.ring_counters(&posted, nullptr, &full, &meta, &comps);
    CHECK(posted + full + meta == nops);
    CHECK(full >= 1);       // the burst actually hit the bound
    CHECK(posted >= 1);     // and the ring still carried work
    CHECK(comps == posted); // every ring op completed via CQE

    // Read-back through the ring confirms both paths committed.
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t i = 0; i < nops; i++) {
        keys.push_back("bp" + std::to_string(i));
        offs.push_back(i * bs);
    }
    std::vector<char> want(seg, seg + nops * bs);
    memset(seg, 0, nops * bs);
    CHECK(conn.get_batch(keys, offs, bs, seg) == 0);
    CHECK(memcmp(seg, want.data(), nops * bs) == 0);

    conn.close();
    server.stop();
}

static void test_ring_doorbell_coalescing() {
    // Submit-side doze/wake discipline: descriptors posted while the
    // server is AWAKE must not pay a doorbell — only a post that finds the
    // parked flag set sends one (the PR 2 empty->non-empty rule,
    // submission half). A burst of bare small ops on this single-core box
    // ping-pongs (each doorbell's eventfd wake hands the CPU to the
    // server, which finishes the op and re-dozes before the next post), so
    // the test pins the server awake with one LARGE head op first: its
    // doorbell unparks the server, whose sliced copy provably outlasts the
    // burst posting loop, and the small posts behind it must then be pure
    // shared memory — zero doorbell frames.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/64));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());

    const size_t nops = 32, nbig = 1024, bs = 16 << 10;  // head op: 16MB
    char* seg = static_cast<char*>(conn.alloc_shm_mr((nbig + nops) * bs));
    CHECK(seg != nullptr);
    memset(seg, 'd', (nbig + nops) * bs);
    std::atomic<int> done{0};
    auto cb = [](void* ctx, int c) {
        if (c == 200) static_cast<std::atomic<int>*>(ctx)->fetch_add(1);
    };
    std::vector<std::string> bigkeys;
    std::vector<uint64_t> bigoffs;
    for (size_t i = 0; i < nbig; i++) {
        bigkeys.push_back("big" + std::to_string(i));
        bigoffs.push_back(i * bs);
    }
    CHECK(conn.put_batch_async(bigkeys, bigoffs, bs, seg, cb, &done) == 0);
    for (size_t i = 0; i < nops; i++)
        CHECK(conn.put_batch_async({"db" + std::to_string(i)},
                                   {(nbig + i) * bs}, bs, seg, cb, &done) == 0);
    for (int i = 0; i < 2500 && done.load() < static_cast<int>(nops) + 1; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(done.load() == static_cast<int>(nops) + 1);

    uint64_t posted = 0, doorbells = 0, full = 0, meta = 0, comps = 0;
    conn.ring_counters(&posted, &doorbells, &full, &meta, &comps);
    CHECK(posted == nops + 1 && full == 0 && meta == 0 && comps == nops + 1);
    // The head op's doorbell plus rare re-doze stragglers (expect 1-2; a
    // descheduled posting thread can let the head op finish mid-burst and
    // re-doze a few times under load) — but never one per op, which is
    // the syscall-per-op regression this plane removes. Half the burst is
    // the loosest bound that still separates the two regimes.
    CHECK(doorbells >= 1);
    CHECK(2 * doorbells < posted);
    std::string st = server.stats_json();
    CHECK(stat_counter(st, "doorbells_rx") == static_cast<long long>(doorbells));
    // CQ-side doorbells can never exceed published completions.
    CHECK(stat_counter(st, "cq_doorbells_tx") <= stat_counter(st, "completions"));
    // Every published completion either paid a CQ doorbell or was elided
    // because the client consumer was awake — the two must account for all
    // of them, and the burst completing behind the sliced head op has to
    // land at least one CQE inside the client's adaptive poll window.
    long long elided = stat_counter(st, "doorbell_elided");
    CHECK(elided >= 1);
    CHECK(stat_counter(st, "cq_doorbells_tx") + elided ==
          stat_counter(st, "completions"));

    conn.close();
    server.stop();
}

static void test_ring_torn_descriptor_rejected() {
    // Generation-tag validation: an advanced sq_tail whose slot gen was
    // never published (a torn/corrupt descriptor) must poison the ring —
    // the server counts it and closes the connection rather than decode
    // garbage. The tamperer maps the segment by name exactly like a buggy
    // second writer would.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/8));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());
    std::string name = conn.ring_name();
    CHECK(!name.empty());

    int fd = shm_open(name.c_str(), O_RDWR, 0);
    CHECK(fd >= 0);
    struct stat stbuf {};
    CHECK(fstat(fd, &stbuf) == 0);
    void* mem = mmap(nullptr, static_cast<size_t>(stbuf.st_size),
                     PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    CHECK(mem != MAP_FAILED);
    ::close(fd);
    RingView view;
    CHECK(ring_view_init(&view, static_cast<char*>(mem),
                         static_cast<uint64_t>(stbuf.st_size)));
    // Publish a tail advance with NO gen write: the consumer must see
    // gen != seq+1 under an advanced tail.
    uint64_t tail = ring_load_acq(&view.ctrl->sq_tail);
    ring_store_rel(&view.ctrl->sq_tail, tail + 1);

    // Nudge the server with socket traffic until it notices; the conn dies.
    bool dead = false;
    for (int i = 0; i < 2500 && !dead; i++) {
        conn.check_exist("poke");  // outcome irrelevant — generates events
        dead = !conn.connected();
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    }
    CHECK(dead);
    std::string st = server.stats_json();
    CHECK(stat_counter(st, "torn_descriptors") == 1);
    CHECK(stat_counter(st, "conns") == 0);  // detached on close
    munmap(mem, static_cast<size_t>(stbuf.st_size));
    conn.close();
    server.stop();
}

static void test_ring_qos_ordering_and_trace() {
    // QoS on the ring path: pending descriptors start foreground-first
    // (a later fg op never waits behind queued bg descriptors), and a
    // traced ring op stamps the same ordered server ticks as the socket
    // path (recv <= first_slice <= last_slice <= done).
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/16));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());

    const size_t nbg = 64, bs = 16 << 10;  // 1MB per bg op = 8 default slices
    char* seg = static_cast<char*>(conn.alloc_shm_mr((3 * nbg + 1) * bs));
    CHECK(seg != nullptr);
    memset(seg, 'q', (3 * nbg + 1) * bs);
    // Completion order via a shared counter captured per-op.
    static std::atomic<int> g_order_next;
    static std::atomic<int> g_order_seq[4];
    g_order_next.store(0);
    for (auto& s : g_order_seq) s.store(-1);
    auto cb2 = [](void* ctx, int c) {
        if (c == 200)
            static_cast<std::atomic<int>*>(ctx)->store(g_order_next.fetch_add(1));
    };
    std::vector<std::string> bgkeys[3];
    std::vector<uint64_t> bgoffs[3];
    for (int b = 0; b < 3; b++)
        for (size_t i = 0; i < nbg; i++) {
            bgkeys[b].push_back("qb" + std::to_string(b) + "_" + std::to_string(i));
            bgoffs[b].push_back((b * nbg + i) * bs);
        }
    const uint64_t tid = 0xabcd1234, span = 0x77;
    for (int b = 0; b < 3; b++)
        CHECK(conn.put_batch_async(bgkeys[b], bgoffs[b], bs, seg, cb2,
                                   &g_order_seq[b], kPriorityBackground) == 0);
    CHECK(conn.put_batch_async({"qfg"}, {3 * nbg * bs}, bs, seg, cb2,
                               &g_order_seq[3], kPriorityForeground, tid,
                               span) == 0);
    for (int i = 0; i < 2500 && g_order_next.load() < 4; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(g_order_next.load() == 4);
    // At most one bg op can already be running when the fg descriptor
    // lands, so foreground completes first or second — never behind the
    // whole background queue.
    CHECK(g_order_seq[3].load() <= 1);
    CHECK(g_order_seq[2].load() > g_order_seq[3].load());

    std::string st = server.stats_json();
    CHECK(stat_counter(st, "bg_ops") >= 3);
    CHECK(stat_counter(st, "recorded") == 1);  // the traced fg op's tick
    size_t at = st.find("\"entries\":[{");
    CHECK(at != std::string::npos);
    std::string entry = st.substr(at);
    long long recv = stat_counter(entry, "recv_us");
    long long first = stat_counter(entry, "first_slice_us");
    long long last = stat_counter(entry, "last_slice_us");
    long long done_us = stat_counter(entry, "done_us");
    CHECK(recv > 0 && recv <= first && first <= last && last <= done_us);
    CHECK(st.find("\"trace_id\":" + std::to_string(tid)) != std::string::npos);

    conn.close();
    server.stop();
}

static void test_ring_batch_slot_wrap() {
    // Multi-op batch slots: a group_begin/end window packs every same-thread
    // async op into ONE slot (RingBatchHdr + per-op RingBatchEntry frames in
    // the slot's meta arena), and the batch format must survive cursor wrap
    // on a tiny ring exactly like the single-op format — byte-correct, every
    // op CQE'd under token base+k, both sides' batch ledgers in lockstep.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/4));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());

    const size_t per = 4, rounds = 12, bs = 16 << 10;  // 12 slots / 4 = 3 wraps
    char* seg = static_cast<char*>(conn.alloc_shm_mr(per * rounds * bs));
    CHECK(seg != nullptr);
    for (size_t i = 0; i < per * rounds * bs; i++)
        seg[i] = static_cast<char>(i * 13 + 5);
    std::atomic<int> done{0};
    auto cb = [](void* ctx, int c) {
        if (c == 200) static_cast<std::atomic<int>*>(ctx)->fetch_add(1);
    };
    for (size_t r = 0; r < rounds; r++) {
        conn.ring_group_begin();
        for (size_t i = 0; i < per; i++) {
            size_t k = r * per + i;
            CHECK(conn.put_batch_async({"bw" + std::to_string(k)}, {k * bs}, bs,
                                       seg, cb, &done) == 0);
        }
        uint64_t mid = 1;
        conn.ring_counters(&mid, nullptr, nullptr, nullptr, nullptr);
        CHECK(mid == r * per);  // captured, not posted, until the window closes
        conn.ring_group_end();
        for (int w = 0; w < 2500 && done.load() < static_cast<int>((r + 1) * per);
             w++)
            std::this_thread::sleep_for(std::chrono::milliseconds(2));
        CHECK(done.load() == static_cast<int>((r + 1) * per));
    }
    uint64_t posted = 0, full = 0, meta = 0, comps = 0;
    conn.ring_counters(&posted, nullptr, &full, &meta, &comps);
    CHECK(posted == rounds * per && comps == rounds * per);
    CHECK(full == 0 && meta == 0);
    uint64_t bslots = 0, bops = 0;
    conn.ring_poll_counters(&bslots, &bops, nullptr, nullptr);
    CHECK(bslots == rounds);       // one slot per flush window...
    CHECK(bops == rounds * per);   // ...carrying the whole window's ops
    std::string st = server.stats_json();
    CHECK(stat_counter(st, "descriptors") == static_cast<long long>(rounds * per));
    CHECK(stat_counter(st, "batch_slots") == static_cast<long long>(rounds));
    CHECK(stat_counter(st, "batch_ops") == static_cast<long long>(rounds * per));
    CHECK(stat_counter(st, "torn_descriptors") == 0);
    CHECK(stat_counter(st, "bad_descriptors") == 0);

    // Read-back through one sync multi-key get (sync ops never join a batch
    // window — the waiter would block before the window could flush).
    std::vector<char> want(seg, seg + per * rounds * bs);
    std::vector<std::string> keys;
    std::vector<uint64_t> offs;
    for (size_t k = 0; k < per * rounds; k++) {
        keys.push_back("bw" + std::to_string(k));
        offs.push_back(k * bs);
    }
    memset(seg, 0, per * rounds * bs);
    CHECK(conn.get_batch(keys, offs, bs, seg) == 0);
    CHECK(memcmp(seg, want.data(), per * rounds * bs) == 0);
    uint64_t bslots2 = 0;
    conn.ring_poll_counters(&bslots2, nullptr, nullptr, nullptr);
    CHECK(bslots2 == bslots);

    conn.close();
    server.stop();
}

static void test_ring_batch_slot_torn_rejected() {
    // Malformed batch slots: a correctly published (gen-tagged) slot whose
    // batch payload is garbage must be rejected with error CQEs — counted as
    // bad_descriptors, never decoded into ops. An untrustworthy header
    // (count out of range) can only fail the base token; a trustworthy count
    // with truncated entries fails every token in the group. Either way the
    // client sees a completion for a token it never issued and fails the
    // connection — the same containment as a torn generation tag.
    for (int variant = 0; variant < 2; variant++) {
        Server server(ring_scfg());
        CHECK(server.start());
        Connection conn(ring_ccfg(server.port(), /*ring_slots=*/8));
        CHECK(conn.connect() == 0);
        CHECK(conn.ring_active());
        std::string name = conn.ring_name();
        CHECK(!name.empty());

        int fd = shm_open(name.c_str(), O_RDWR, 0);
        CHECK(fd >= 0);
        struct stat stbuf {};
        CHECK(fstat(fd, &stbuf) == 0);
        void* mem = mmap(nullptr, static_cast<size_t>(stbuf.st_size),
                         PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
        CHECK(mem != MAP_FAILED);
        ::close(fd);
        RingView view;
        CHECK(ring_view_init(&view, static_cast<char*>(mem),
                             static_cast<uint64_t>(stbuf.st_size)));
        uint64_t seq = ring_load_acq(&view.ctrl->sq_tail);
        // variant 0: count=0 — header untrustworthy, one error CQE on the
        // base token. variant 1: count=3 but zero entry bytes behind the
        // header — all three tokens error-CQE'd.
        RingBatchHdr hdr{static_cast<uint16_t>(variant == 0 ? 0 : 3), 0};
        memcpy(view.meta_at(seq), &hdr, sizeof(hdr));
        RingSlot* s = view.slot(seq);
        s->token = 0xdead0000;
        s->meta_len = sizeof(RingBatchHdr);
        s->op = 0;
        s->flags = kRingSlotFlagBatch;
        s->reserved = 0;
        ring_store_rel(&s->gen, seq + 1);
        ring_store_rel(&view.ctrl->sq_tail, seq + 1);

        bool dead = false;
        for (int i = 0; i < 2500 && !dead; i++) {
            conn.check_exist("poke");  // outcome irrelevant — generates events
            dead = !conn.connected();
            std::this_thread::sleep_for(std::chrono::milliseconds(2));
        }
        CHECK(dead);  // the unknown-token CQE poisons the client side
        std::string st = server.stats_json();
        CHECK(stat_counter(st, "bad_descriptors") == (variant == 0 ? 1 : 3));
        CHECK(stat_counter(st, "torn_descriptors") == 0);
        CHECK(stat_counter(st, "batch_slots") == 0);  // malformed != batched
        munmap(mem, static_cast<size_t>(stbuf.st_size));
        conn.close();
        server.stop();
    }
}

static void test_ring_batch_slot_qos_ordering() {
    // QoS across ONE batch slot: the server decodes the whole slot before
    // starting any op and queues per priority class, so a foreground op
    // packed BEHIND background ops in the same slot still starts first —
    // batching must not flatten priorities into slot order.
    Server server(ring_scfg());
    CHECK(server.start());
    Connection conn(ring_ccfg(server.port(), /*ring_slots=*/16));
    CHECK(conn.connect() == 0);
    CHECK(conn.ring_active());

    constexpr size_t nbg = 3;
    const size_t nblk = 64, bs = 16 << 10;  // 1MB per bg op = 8 default slices
    char* seg = static_cast<char*>(conn.alloc_shm_mr((nbg * nblk + 1) * bs));
    CHECK(seg != nullptr);
    memset(seg, 'b', (nbg * nblk + 1) * bs);
    static std::atomic<int> g_bseq_next;
    static std::atomic<int> g_bseq[nbg + 1];
    g_bseq_next.store(0);
    for (auto& s : g_bseq) s.store(-1);
    auto cb = [](void* ctx, int c) {
        if (c == 200)
            static_cast<std::atomic<int>*>(ctx)->store(g_bseq_next.fetch_add(1));
    };
    conn.ring_group_begin();
    for (size_t b = 0; b < nbg; b++) {
        std::vector<std::string> keys;
        std::vector<uint64_t> offs;
        for (size_t i = 0; i < nblk; i++) {
            keys.push_back("bq" + std::to_string(b) + "_" + std::to_string(i));
            offs.push_back((b * nblk + i) * bs);
        }
        CHECK(conn.put_batch_async(keys, offs, bs, seg, cb, &g_bseq[b],
                                   kPriorityBackground) == 0);
    }
    CHECK(conn.put_batch_async({"bqfg"}, {nbg * nblk * bs}, bs, seg, cb,
                               &g_bseq[nbg], kPriorityForeground) == 0);
    conn.ring_group_end();
    for (int i = 0; i < 2500 && g_bseq_next.load() < static_cast<int>(nbg) + 1; i++)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
    CHECK(g_bseq_next.load() == static_cast<int>(nbg) + 1);
    // The slot lands whole, so nothing can be running when the fg op is
    // queued: foreground completes strictly first, background keeps FIFO.
    CHECK(g_bseq[nbg].load() == 0);
    for (size_t b = 0; b < nbg; b++)
        CHECK(g_bseq[b].load() == static_cast<int>(b) + 1);

    uint64_t bslots = 0, bops = 0;
    conn.ring_poll_counters(&bslots, &bops, nullptr, nullptr);
    CHECK(bslots == 1 && bops == nbg + 1);
    std::string st = server.stats_json();
    CHECK(stat_counter(st, "batch_slots") == 1);
    CHECK(stat_counter(st, "batch_ops") == static_cast<long long>(nbg) + 1);
    CHECK(stat_counter(st, "bg_ops") >= static_cast<long long>(nbg));

    conn.close();
    server.stop();
}

static void test_opstats_percentile_accuracy() {
    // The HDR-style histogram must report percentiles within ~3% — 32
    // sub-buckets per octave (kSubBits=5, ~2.2% quantization) feed both
    // the derived p50/p99 gauges and the /metrics duration histogram
    // (docs/observability.md).
    for (uint64_t center : {7ull, 23ull, 150ull, 1234ull, 87654ull}) {
        OpStats s;
        std::vector<uint64_t> vals;
        for (int d = -40; d <= 40; d++) {
            uint64_t us = static_cast<uint64_t>(
                static_cast<double>(center) * (1.0 + 0.004 * d));
            vals.push_back(us);
            s.record(us, 0, 0, true);
        }
        std::sort(vals.begin(), vals.end());
        double true_p50 = static_cast<double>(vals[vals.size() / 2]);
        double got = s.p50_us();
        double err = std::abs(got - true_p50) / true_p50;
        CHECK(err <= 0.03);
    }
    OpStats empty;
    CHECK(empty.p50_us() == 0.0);
    OpStats one;
    one.record(100, 0, 0, true);
    CHECK(std::abs(one.p99_us() - 100.0) / 100.0 <= 0.03);
    // bucket_le_us is the inverse upper bound of the bucketing: every
    // recorded value must fall at or below its bucket's `le`, and the
    // `le` sequence the /metrics histogram renders must be monotone.
    OpStats hb;
    for (uint64_t us : {0ull, 5ull, 31ull, 32ull, 1000ull, 123456ull})
        hb.record(us, 0, 0, true);
    uint64_t prev_le = 0;
    uint64_t seen = 0;
    for (int b = 0; b < OpStats::kBuckets; b++) {
        if (hb.lat_buckets[b] == 0) continue;
        uint64_t le = OpStats::bucket_le_us(b);
        CHECK(le >= prev_le);
        prev_le = le;
        seen += hb.lat_buckets[b];
    }
    CHECK(seen == hb.count);
    CHECK(OpStats::bucket_le_us(0) == 0 && OpStats::bucket_le_us(31) == 31);
}

int main() {
    set_log_level(LogLevel::kError);
    test_opstats_percentile_accuracy();
    test_mempool_basic();
    test_mempool_exhaustion_and_rollback();
    test_kvstore_lru_eviction();
    test_kvstore_overwrite_slot();
    test_spill_tier_demote_promote();
    test_wire_codec_roundtrip();
    test_qos_wire_priority_tag();
    test_trace_wire_context();
    test_trace_ring_loopback(/*enable_shm=*/true);
    test_trace_ring_loopback(/*enable_shm=*/false);
    test_qos_two_level_scheduler();
    test_ring_wrap_and_disable();
    test_ring_full_backpressure();
    test_ring_doorbell_coalescing();
    test_ring_torn_descriptor_rejected();
    test_ring_qos_ordering_and_trace();
    test_ring_batch_slot_wrap();
    test_ring_batch_slot_torn_rejected();
    test_ring_batch_slot_qos_ordering();
    test_loopback_end_to_end(/*enable_shm=*/true);
    test_loopback_end_to_end(/*enable_shm=*/false);
    test_completion_ring(/*enable_shm=*/true);
    test_completion_ring(/*enable_shm=*/false);
    test_abandoned_sync_ops_stress(/*enable_shm=*/true);
    test_abandoned_sync_ops_stress(/*enable_shm=*/false);
    if (g_failures == 0) {
        printf("native tests: all passed\n");
        return 0;
    }
    fprintf(stderr, "native tests: %d failure(s)\n", g_failures.load());
    return 1;
}
