#include "its/client.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdlib>
#include <future>
#include <random>

#include "its/iovec_util.h"
#include "its/net_util.h"
#include "its/log.h"
#include "its/mempool.h"  // shm_registry_* (crash-time segment cleanup)
#include "its/ring.h"

namespace its {

// Descriptor-ring segment (docs/descriptor_ring.md): mapped view + the shm
// name needed to unlink it at close.
struct Connection::RingState {
    RingView view;
    std::string name;
};

// Shared landing zone for sync ops. The waiter and the Request each hold a
// reference, so a caller that times out can abandon the wait and a late
// completion still has a live place to write (no use-after-free).
struct Connection::SyncState {
    std::promise<void> prom;
    // Set for one-RTT segment ops (kOpPutFrom/kOpGetInto): the SERVER moves
    // bytes in the client's mapped segment, so an abandoned op cannot be
    // made safe by client-side drains — the timeout POISONS the connection
    // (see sync_roundtrip) and the segment views die with it.
    bool seg_op = false;
    uint32_t status = kStatusUnavailable;
    std::vector<uint8_t> body;
    uint8_t* payload = nullptr;  // malloc'd; freed here unless the waiter takes it
    size_t payload_size = 0;
    // Set by a timed-out waiter. From that moment the caller may free the
    // buffers the request's iovecs point at, so the reactor must never touch
    // them again: unsent requests are dropped, late get payloads are drained
    // into scratch, and a request half-streamed from caller memory fails the
    // connection (it has been wedged for op_timeout_ms anyway).
    std::atomic<bool> abandoned{false};

    ~SyncState() {
        if (payload != nullptr) free(payload);
    }
};

// RAII bracket for reactor regions that touch caller memory: io_seq_ odd
// while inside. Paired with SyncState::abandoned (see client.h io_seq_).
namespace {
uint64_t now_us() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000ull + ts.tv_nsec / 1000;
}

struct IoSection {
    std::atomic<uint64_t>& seq;
    explicit IoSection(std::atomic<uint64_t>& s) : seq(s) { seq.fetch_add(1); }
    ~IoSection() { seq.fetch_add(1); }
    IoSection(const IoSection&) = delete;
    IoSection& operator=(const IoSection&) = delete;
};
}  // namespace

struct Connection::Request {
    uint8_t op = 0;
    ReqHeader hdr{};
    std::vector<uint8_t> body;
    std::vector<iovec> tx_payload;  // gather sources (user memory / caller buffer)
    size_t sent = 0;
    size_t send_total = 0;
    // Shm fast path: tx_payload/rx_addrs are memcpy endpoints, not wire
    // payload (payload_on_wire=false), and release requests expect no
    // response from the server.
    bool payload_on_wire = true;
    bool no_response = false;

    // Payload owned by the request itself (sync ops that may be abandoned on
    // timeout must not reference caller memory from tx_payload).
    std::vector<uint8_t> owned_payload;

    // get-batch scatter destinations (filled sizes arrive in the resp body)
    std::vector<char*> rx_addrs;
    uint32_t block_size = 0;
    bool alloc_rx = false;  // tcp_get/stat: malloc a payload buffer

    // async completion
    CompletionCb cb = nullptr;
    void* ctx = nullptr;

    // sync completion
    std::shared_ptr<SyncState> sync;

    // reactor-side response capture
    uint8_t* rx_buf = nullptr;
    size_t rx_buf_size = 0;

    // (Re)compute the wire framing before (re)queueing for send.
    void prime() {
        hdr = ReqHeader{kMagic, op, static_cast<uint32_t>(body.size())};
        sent = 0;
        send_total = sizeof(ReqHeader) + body.size();
        if (payload_on_wire)
            for (const auto& io : tx_payload) send_total += io.iov_len;
    }
};

Connection::Connection(const ClientConfig& config) : config_(config) {}

Connection::~Connection() { close(); }

int Connection::connect() {
    install_crash_handler();  // reference installs in setup (:245-249)
    if (connected_.load()) return 0;

    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port = std::to_string(config_.port);
    int rc = getaddrinfo(config_.host.c_str(), port.c_str(), &hints, &res);
    if (rc != 0 || res == nullptr) {
        ITS_LOG_ERROR("resolve %s failed: %s", config_.host.c_str(), gai_strerror(rc));
        return -EHOSTUNREACH;
    }

    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) {
        freeaddrinfo(res);
        return -errno;
    }
    // Nonblocking connect with a poll() deadline (connect_timeout_ms).
    fcntl(fd_, F_SETFL, fcntl(fd_, F_GETFL, 0) | O_NONBLOCK);
    rc = ::connect(fd_, res->ai_addr, res->ai_addrlen);
    freeaddrinfo(res);
    if (rc != 0 && errno != EINPROGRESS) {
        rc = -errno;
        ::close(fd_);
        fd_ = -1;
        return rc;
    }
    if (rc != 0) {
        pollfd pfd{fd_, POLLOUT, 0};
        rc = poll(&pfd, 1, config_.connect_timeout_ms);
        int err = 0;
        socklen_t elen = sizeof(err);
        getsockopt(fd_, SOL_SOCKET, SO_ERROR, &err, &elen);
        if (rc <= 0 || err != 0) {
            ::close(fd_);
            fd_ = -1;
            return rc <= 0 ? -ETIMEDOUT : -err;
        }
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    // SO_SNDBUF/SO_RCVBUF intentionally left to kernel autotuning (see
    // server accept path).
    set_pacing_rate(fd_, config_.pacing_rate_mbps, "client");

    epoll_fd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd_, &ev);
    ev.data.fd = wake_fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);

    stop_.store(false);
    connected_.store(true);
    thread_ = std::thread([this] { reactor(); });
    if (config_.enable_shm) shm_handshake();
    if (shm_ok_.load() && config_.enable_ring) ring_setup();
    ITS_LOG_DEBUG("connected to %s:%d (shm=%d ring=%d)", config_.host.c_str(), config_.port,
                  static_cast<int>(shm_ok_.load()), static_cast<int>(ring_ok_.load()));
    return 0;
}

// Probe the server's shm pool directory and map every pool. All-or-nothing:
// a partially mapped directory (e.g. cross-host client that happens to share
// an shm namespace) disables the fast path rather than risking per-op
// failures.
void Connection::shm_handshake() {
    auto req = std::make_unique<Request>();
    req->op = kOpShmHello;
    std::vector<uint8_t> body;
    // Bounded wait: connect() promises connect_timeout_ms overall; a server
    // that accepted but never answers must not hang the caller forever.
    uint32_t status =
        sync_roundtrip(std::move(req), &body, nullptr, nullptr, config_.connect_timeout_ms);
    if (status != kStatusOk || body.empty()) return;
    try {
        ShmLocResp resp = ShmLocResp::decode(body.data(), body.size());
        if (resp.pools.empty()) return;
        size_t mapped = 0;
        for (const auto& p : resp.pools)
            if (map_pool(p.pool_id, p.name, p.size) != nullptr) mapped++;
        shm_ok_.store(mapped == resp.pools.size());
    } catch (const std::exception& e) {
        ITS_LOG_WARN("shm handshake parse failed: %s", e.what());
    }
}

char* Connection::map_pool(uint16_t pool_id, const std::string& name, uint64_t size) {
    {
        std::lock_guard<std::mutex> lock(shm_mu_);
        auto it = shm_pools_.find(pool_id);
        if (it != shm_pools_.end()) return it->second.base;
    }
    int fd = shm_open(name.c_str(), O_RDWR, 0);
    if (fd < 0) return nullptr;
    void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (mem == MAP_FAILED) return nullptr;
    std::lock_guard<std::mutex> lock(shm_mu_);
    auto [it, inserted] = shm_pools_.emplace(pool_id, ShmMap{static_cast<char*>(mem), size});
    if (!inserted) munmap(mem, size);  // lost a race; keep the existing mapping
    return it->second.base;
}

// Create the descriptor-ring segment and ask the server to attach it.
// Failure at any step is silent degradation: the socket path stays
// byte-identical and every batched op keeps working.
void Connection::ring_setup() {
    uint32_t slots = config_.ring_slots != 0 ? config_.ring_slots : kRingSqSlots;
    if (slots < 2 || (slots & (slots - 1)) != 0) {
        ITS_LOG_WARN("ring_slots=%u invalid (need power of two >= 2); using %u",
                     config_.ring_slots, kRingSqSlots);
        slots = kRingSqSlots;
    }
    uint64_t bytes = ring_segment_bytes(slots, slots, kRingMetaStride);
    char name[96];
    std::random_device rd;
    snprintf(name, sizeof(name), "/its.%d.%08x.ring", static_cast<int>(getpid()), rd());
    int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return;
    // Liveness marker for shm_sweep_stale (see alloc_shm_mr): the flock'd fd
    // is intentionally leaked for the connection lifetime.
    flock(fd, LOCK_EX | LOCK_NB);
    if (ftruncate(fd, static_cast<off_t>(bytes)) != 0 ||
        posix_fallocate(fd, 0, static_cast<off_t>(bytes)) != 0) {
        ::close(fd);
        shm_unlink(name);
        return;
    }
    void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (mem == MAP_FAILED) {
        ::close(fd);
        shm_unlink(name);
        return;
    }
    shm_registry_add(name);
    // The segment is zero-filled; publish the geometry (plain writes — the
    // server cannot see it until the attach below).
    RingCtrl* ctrl = static_cast<RingCtrl*>(mem);
    ctrl->magic = kRingMagic;
    ctrl->version = kRingVersion;
    ctrl->sq_slots = slots;
    ctrl->cq_slots = slots;
    ctrl->slot_bytes = sizeof(RingSlot);
    ctrl->cqe_bytes = sizeof(RingCqe);
    ctrl->meta_stride = kRingMetaStride;
    auto state = std::make_unique<RingState>();
    if (!ring_view_init(&state->view, static_cast<char*>(mem), bytes)) {
        munmap(mem, bytes);
        shm_registry_remove(name);
        shm_unlink(name);
        return;
    }
    state->name = name;
    auto req = std::make_unique<Request>();
    req->op = kOpRingAttach;
    RingMeta{name, bytes}.encode(req->body);
    uint32_t status =
        sync_roundtrip(std::move(req), nullptr, nullptr, nullptr, config_.connect_timeout_ms);
    if (status != kStatusOk) {
        munmap(mem, bytes);
        shm_registry_remove(name);
        shm_unlink(name);
        ITS_LOG_DEBUG("server declined descriptor ring (%u); socket path only", status);
        return;
    }
    dring_ = std::move(state);
    ring_ok_.store(true);
    ITS_LOG_DEBUG("descriptor ring %s attached (%u slots)", name, slots);
}

void Connection::ring_teardown() {
    ring_ok_.store(false);
    std::lock_guard<std::mutex> lock(dring_mu_);  // vs a late try_ring_post
    if (dring_ == nullptr) return;
    munmap(dring_->view.base, dring_->view.size);
    shm_registry_remove(dring_->name.c_str());
    shm_unlink(dring_->name.c_str());
    dring_.reset();
    ring_sq_seq_ = 0;
    ring_cq_seq_ = 0;
}

std::string Connection::ring_name() const {
    std::lock_guard<std::mutex> lock(dring_mu_);
    return dring_ != nullptr ? dring_->name : std::string();
}

void Connection::ring_counters(uint64_t* posted, uint64_t* doorbells,
                               uint64_t* full_fallbacks, uint64_t* meta_fallbacks,
                               uint64_t* completions) const {
    if (posted != nullptr) *posted = ring_posted_.load(std::memory_order_relaxed);
    if (doorbells != nullptr) *doorbells = ring_doorbells_.load(std::memory_order_relaxed);
    if (full_fallbacks != nullptr)
        *full_fallbacks = ring_full_fallbacks_.load(std::memory_order_relaxed);
    if (meta_fallbacks != nullptr)
        *meta_fallbacks = ring_meta_fallbacks_.load(std::memory_order_relaxed);
    if (completions != nullptr)
        *completions = ring_completions_.load(std::memory_order_relaxed);
}

// Post a built segment op as a ring descriptor: its body (the SegBatchMeta
// encoding the socket path would have sent) is copied into the slot's meta
// region and published with a generation tag — no socket write, no syscall,
// unless the server has parked itself (then exactly one doorbell frame).
int Connection::try_ring_post(std::unique_ptr<Request>* reqp) {
    Request* req = reqp->get();
    bool doorbell = false;
    {
        std::lock_guard<std::mutex> lock(dring_mu_);
        // Re-check under the lock: a concurrent close() tears the ring down
        // after failing the connection.
        if (dring_ == nullptr || !connected_.load()) return -1;
        RingView& v = dring_->view;
        if (req->body.size() > v.meta_stride) {
            ring_meta_fallbacks_.fetch_add(1, std::memory_order_relaxed);
            return -1;
        }
        // Open batch group, posted by its owning thread: capture instead of
        // publishing — ring_group_end packs the whole flush into batch
        // slots. Sync ops never join (their waiter blocks before the group
        // could flush); an op too big to share a slot with even the batch
        // header takes the plain single-op slot below.
        if (group_active_ && req->sync == nullptr &&
            group_owner_ == std::this_thread::get_id() &&
            sizeof(RingBatchHdr) + sizeof(RingBatchEntry) + req->body.size() <=
                v.meta_stride) {
            group_reqs_.push_back(std::move(*reqp));
            return 0;
        }
        uint64_t head = ring_load_acq(&v.ctrl->sq_head);
        if (ring_sq_seq_ - head >= v.sq_slots ||
            ring_inflight_.size() >= v.cq_slots) {
            // Ring-full backpressure: the op rides the socket path instead
            // of blocking the caller (the async submitter may be an event
            // loop). Counted — the bench watches this.
            ring_full_fallbacks_.fetch_add(1, std::memory_order_relaxed);
            return -1;
        }
        doorbell = ring_publish_one_locked(std::move(*reqp));
    }
    if (doorbell) {
        // The server parked in epoll: wake it with one 9-byte frame. While
        // it is awake (the common case under load), posts are socket-free.
        ring_doorbells_.fetch_add(1, std::memory_order_relaxed);
        auto db = std::make_unique<Request>();
        db->op = kOpRingDoorbell;
        db->no_response = true;
        submit(std::move(db));
    }
    return 0;
}

bool Connection::ring_publish_one_locked(std::unique_ptr<Request> req) {
    RingView& v = dring_->view;
    uint64_t seq = ring_sq_seq_;
    uint64_t token = ring_next_token_++;
    memcpy(v.meta_at(seq), req->body.data(), req->body.size());
    RingSlot* s = v.slot(seq);
    s->token = token;
    s->meta_len = static_cast<uint32_t>(req->body.size());
    s->op = req->op;
    s->flags = 0;
    s->reserved = 0;
    ring_store_rel(&s->gen, seq + 1);
    ring_inflight_.emplace(token, std::move(req));
    ring_sq_seq_ = seq + 1;
    ring_store_rel(&v.ctrl->sq_tail, seq + 1);
    ring_posted_.fetch_add(1, std::memory_order_relaxed);
    ring_fence();
    return ring_flag_take(&v.ctrl->srv_waiting);
}

void Connection::ring_group_begin() {
    std::lock_guard<std::mutex> lock(dring_mu_);
    if (group_active_) return;  // first opener wins; others post plain
    group_active_ = true;
    group_owner_ = std::this_thread::get_id();
}

void Connection::ring_group_end() {
    std::vector<std::unique_ptr<Request>> overflow;
    bool doorbell = false;
    {
        std::lock_guard<std::mutex> lock(dring_mu_);
        if (!group_active_) return;
        group_active_ = false;
        if (group_reqs_.empty()) return;
        std::vector<std::unique_ptr<Request>> reqs = std::move(group_reqs_);
        group_reqs_.clear();
        if (dring_ == nullptr || !connected_.load()) {
            overflow = std::move(reqs);
        } else {
            RingView& v = dring_->view;
            size_t i = 0;
            while (i < reqs.size()) {
                uint64_t head = ring_load_acq(&v.ctrl->sq_head);
                if (ring_sq_seq_ - head >= v.sq_slots) break;
                // Greedy pack: how many of the remaining ops share this slot
                // (meta-arena capacity, per-slot op bound, CQ in-flight cap
                // — each packed op consumes one completion entry).
                size_t fit = 0;
                size_t off = sizeof(RingBatchHdr);
                while (i + fit < reqs.size() && fit < kRingBatchMaxOps &&
                       ring_inflight_.size() + fit < v.cq_slots) {
                    size_t need = sizeof(RingBatchEntry) + reqs[i + fit]->body.size();
                    if (off + need > v.meta_stride) break;
                    off += need;
                    fit++;
                }
                if (fit == 0) break;  // in-flight cap (bodies fit by capture check)
                if (fit == 1) {
                    // A lone op posts in the plain single-op format — batch
                    // framing buys nothing and the server skips a decode hop.
                    doorbell |= ring_publish_one_locked(std::move(reqs[i]));
                    i++;
                    continue;
                }
                uint64_t seq = ring_sq_seq_;
                char* arena = v.meta_at(seq);
                uint64_t base = ring_next_token_;
                RingBatchHdr hdr{static_cast<uint16_t>(fit), 0};
                memcpy(arena, &hdr, sizeof(hdr));
                size_t w = sizeof(RingBatchHdr);
                for (size_t k = 0; k < fit; k++) {
                    Request* rq = reqs[i + k].get();
                    RingBatchEntry ent{static_cast<uint32_t>(rq->body.size()), rq->op,
                                       0, 0};
                    memcpy(arena + w, &ent, sizeof(ent));
                    memcpy(arena + w + sizeof(ent), rq->body.data(), rq->body.size());
                    w += sizeof(ent) + rq->body.size();
                }
                RingSlot* s = v.slot(seq);
                s->token = base;  // op k completes under token base + k
                s->meta_len = static_cast<uint32_t>(w);
                s->op = 0;
                s->flags = kRingSlotFlagBatch;
                s->reserved = 0;
                ring_store_rel(&s->gen, seq + 1);
                for (size_t k = 0; k < fit; k++)
                    ring_inflight_.emplace(base + k, std::move(reqs[i + k]));
                ring_next_token_ += fit;
                ring_sq_seq_ = seq + 1;
                ring_store_rel(&v.ctrl->sq_tail, seq + 1);
                ring_posted_.fetch_add(fit, std::memory_order_relaxed);
                ring_batch_slots_.fetch_add(1, std::memory_order_relaxed);
                ring_batch_ops_.fetch_add(fit, std::memory_order_relaxed);
                ring_fence();
                doorbell |= ring_flag_take(&v.ctrl->srv_waiting);
                i += fit;
            }
            // Whatever did not fit rides the socket path — the same counted
            // ring-full backpressure as the plain path, never an error.
            for (; i < reqs.size(); i++) {
                ring_full_fallbacks_.fetch_add(1, std::memory_order_relaxed);
                overflow.push_back(std::move(reqs[i]));
            }
        }
    }
    if (doorbell) {
        ring_doorbells_.fetch_add(1, std::memory_order_relaxed);
        auto db = std::make_unique<Request>();
        db->op = kOpRingDoorbell;
        db->no_response = true;
        submit(std::move(db));
    }
    if (!overflow.empty()) {
        // Inline the submit() enqueue so a refused op (connection already
        // failed) can still be completed instead of silently dropped, and
        // the whole spill shares one reactor wake.
        size_t queued = 0;
        for (auto& r : overflow) {
            bool sent = false;
            {
                std::lock_guard<std::mutex> lock(submit_mu_);
                if (connected_.load()) {
                    r->prime();
                    submitted_.push_back(std::move(r));
                    sent = true;
                }
            }
            if (sent)
                queued++;
            else
                complete(std::move(r), static_cast<int>(kStatusUnavailable),
                         /*take_body=*/false);
        }
        if (queued > 0) {
            uint64_t one = 1;
            ssize_t rc = write(wake_fd_, &one, sizeof(one));
            (void)rc;
        }
    }
}

void Connection::ring_poll_counters(uint64_t* batch_slots, uint64_t* batch_ops,
                                    uint64_t* poll_hits, uint64_t* poll_arms) const {
    if (batch_slots != nullptr)
        *batch_slots = ring_batch_slots_.load(std::memory_order_relaxed);
    if (batch_ops != nullptr)
        *batch_ops = ring_batch_ops_.load(std::memory_order_relaxed);
    if (poll_hits != nullptr)
        *poll_hits = ring_poll_hits_.load(std::memory_order_relaxed);
    if (poll_arms != nullptr)
        *poll_arms = ring_poll_arms_.load(std::memory_order_relaxed);
}

// Reactor-side completion-ring drain. Returns false only on a corrupt ring
// (generation-tag mismatch / unknown token), which fails the connection.
bool Connection::drain_cq() {
    if (!ring_ok_.load(std::memory_order_acquire)) return true;
    RingView& v = dring_->view;
    while (ring_load_acq(&v.ctrl->cq_tail) != ring_cq_seq_) {
        RingCqe* e = v.cqe(ring_cq_seq_);
        if (ring_load_acq(&e->gen) != ring_cq_seq_ + 1) {
            ITS_LOG_ERROR("ring: torn completion at seq %llu",
                          static_cast<unsigned long long>(ring_cq_seq_));
            return false;
        }
        uint64_t token = e->token;
        uint32_t status = e->status;
        std::unique_ptr<Request> req;
        {
            std::lock_guard<std::mutex> lock(dring_mu_);
            auto it = ring_inflight_.find(token);
            if (it != ring_inflight_.end()) {
                req = std::move(it->second);
                ring_inflight_.erase(it);
            }
        }
        ring_cq_seq_++;
        ring_store_rel(&v.ctrl->cq_head, ring_cq_seq_);
        if (req == nullptr) {
            ITS_LOG_ERROR("ring: completion for unknown token");
            return false;
        }
        ring_completions_.fetch_add(1, std::memory_order_relaxed);
        // Feed the adaptive poll budget: back-to-back completions pull the
        // gap EWMA toward zero (poll hard), a quiet ring pushes it past the
        // cap (park immediately). Reactor-owned state, no lock.
        ring_gap_note(&ring_gap_ewma_us_, &ring_last_cqe_us_, now_us());
        complete(std::move(req), static_cast<int>(status), /*take_body=*/false);
    }
    return true;
}

int Connection::submit_any(std::unique_ptr<Request> req) {
    if (ring_ok_.load(std::memory_order_acquire) &&
        (req->op == kOpPutFrom || req->op == kOpGetInto)) {
        if (try_ring_post(&req) == 0) return 0;
    }
    return submit(std::move(req));
}

void Connection::close() {
    if (fd_ < 0) return;
    stop_.store(true);
    uint64_t one = 1;
    ssize_t rc = write(wake_fd_, &one, sizeof(one));
    (void)rc;
    if (thread_.joinable()) thread_.join();
    ::close(fd_);
    ::close(wake_fd_);
    ::close(epoll_fd_);
    fd_ = wake_fd_ = epoll_fd_ = -1;
    connected_.store(false);
    shm_ok_.store(false);
    ring_teardown();  // in-flight ring ops were failed by the reactor's fail_all
    {
        std::lock_guard<std::mutex> lock(shm_mu_);
        for (auto& [id, m] : shm_pools_) munmap(m.base, m.size);
        shm_pools_.clear();
    }
    std::lock_guard<std::mutex> lock(mr_mu_);
    for (auto& seg : client_segs_) {
        munmap(seg.base, seg.size);
        if (!seg.name.empty()) {
            shm_unlink(seg.name.c_str());
            shm_registry_remove(seg.name.c_str());
        }
    }
    client_segs_.clear();
    regions_.clear();
}

int Connection::register_mr(void* ptr, size_t size) {
    // Best-effort pin: mlock failure (RLIMIT_MEMLOCK in containers) degrades
    // to unpinned but the region is still registered for validation. Warn
    // once — per-transfer registrations would otherwise spam the log.
    if (mlock(ptr, size) != 0) {
        static std::atomic<bool> warned{false};
        if (!warned.exchange(true))
            ITS_LOG_WARN("mlock(%zu) failed (%s); regions registered unpinned", size,
                         strerror(errno));
    }
    std::lock_guard<std::mutex> lock(mr_mu_);
    regions_.emplace_back(static_cast<const char*>(ptr), size);
    return 0;
}

int Connection::unregister_mr(void* ptr) {
    // Drops the most recent region with this base (transfer-scoped
    // registrations of short-lived host buffers; the reference instead keeps
    // an ever-growing MR cache, reference src/libinfinistore.cpp:702-733).
    std::lock_guard<std::mutex> lock(mr_mu_);
    for (auto it = regions_.rbegin(); it != regions_.rend(); ++it) {
        if (it->first == static_cast<const char*>(ptr)) {
            const char* base = it->first;
            size_t size = it->second;
            regions_.erase(std::next(it).base());
            // munlock unpins whole pages no matter how many registrations
            // cover them, so a duplicate/overlapping registration must keep
            // its pages pinned when this one goes. Subtract every surviving
            // region (expanded to page boundaries, since a shared boundary
            // page must also stay pinned) and unpin only what remains.
            const size_t pg = static_cast<size_t>(sysconf(_SC_PAGESIZE));
            std::vector<std::pair<const char*, const char*>> unpin{
                {base, base + size}};
            for (const auto& [rs, rsz] : regions_) {
                const char* lo = reinterpret_cast<const char*>(
                    reinterpret_cast<uintptr_t>(rs) / pg * pg);
                const char* hi = reinterpret_cast<const char*>(
                    (reinterpret_cast<uintptr_t>(rs + rsz) + pg - 1) / pg * pg);
                std::vector<std::pair<const char*, const char*>> next;
                for (auto [a, b] : unpin) {
                    if (hi <= a || lo >= b) {
                        next.emplace_back(a, b);
                        continue;
                    }
                    if (a < lo) next.emplace_back(a, lo);
                    if (hi < b) next.emplace_back(hi, b);
                }
                unpin.swap(next);
            }
            for (auto [a, b] : unpin)
                munlock(const_cast<char*>(a), static_cast<size_t>(b - a));
            return 0;
        }
    }
    return -1;
}

bool Connection::base_registered(const void* base, size_t span) const {
    const char* p = static_cast<const char*>(base);
    std::lock_guard<std::mutex> lock(mr_mu_);
    for (const auto& [start, size] : regions_) {
        if (p >= start && p + span <= start + size) return true;
    }
    return false;
}

const Connection::ClientSeg* Connection::find_seg(const void* base, size_t span) const {
    const char* p = static_cast<const char*>(base);
    std::lock_guard<std::mutex> lock(mr_mu_);
    for (const auto& seg : client_segs_) {
        if (seg.server_mapped && p >= seg.base && p + span <= seg.base + seg.size)
            return &seg;
    }
    return nullptr;
}

void* Connection::alloc_shm_mr(size_t size) {
    if (!config_.enable_shm || !connected_.load() || size == 0) return nullptr;
    static std::atomic<uint32_t> counter{0};
    uint32_t seq = counter.fetch_add(1);
    char name[96];
    std::random_device rd;
    snprintf(name, sizeof(name), "/its.%d.%08x.c%u", static_cast<int>(getpid()), rd(), seq);
    int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return nullptr;
    // Liveness marker for shm_sweep_stale, taken before the (possibly long)
    // fallocate so a server starting concurrently cannot sweep the segment
    // mid-setup.
    flock(fd, LOCK_EX | LOCK_NB);
    if (ftruncate(fd, static_cast<off_t>(size)) != 0 ||
        posix_fallocate(fd, 0, static_cast<off_t>(size)) != 0) {
        ::close(fd);
        shm_unlink(name);
        return nullptr;
    }
    void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (mem == MAP_FAILED) {
        ::close(fd);
        shm_unlink(name);
        return nullptr;
    }
    // Leak fd intentionally: it holds the flock for the connection lifetime
    // (closed implicitly at process exit; the segment itself is unlinked in
    // close()).
    shm_registry_add(name);

    ClientSeg seg;
    seg.base = static_cast<char*>(mem);
    seg.size = size;
    seg.name = name;
    seg.id = static_cast<uint16_t>(seq);  // process-unique (mod 64k), per-conn on the server

    // Ask the server to map it; a remote/shm-less server answers non-200 and
    // we fall back to a plain (still registered) buffer.
    auto req = std::make_unique<Request>();
    req->op = kOpRegSegment;
    SegMeta{seg.id, seg.name, static_cast<uint64_t>(size)}.encode(req->body);
    uint32_t status =
        sync_roundtrip(std::move(req), nullptr, nullptr, nullptr, config_.connect_timeout_ms);
    std::lock_guard<std::mutex> lock(mr_mu_);
    regions_.emplace_back(seg.base, size);  // valid base for every path
    if (status == kStatusOk) {
        seg.server_mapped = true;
        ITS_LOG_DEBUG("shm segment %s (%zu bytes) registered with server", name, size);
    } else {
        shm_registry_remove(name);
        shm_unlink(name);  // mapping stays valid locally until munmap
        seg.name.clear();
        ITS_LOG_DEBUG("server declined shm segment (%u); using plain buffer", status);
    }
    client_segs_.push_back(seg);
    return mem;
}

int Connection::submit(std::unique_ptr<Request> req) {
    req->prime();
    {
        std::lock_guard<std::mutex> lock(submit_mu_);
        if (!connected_.load()) return -1;
        submitted_.push_back(std::move(req));
    }
    uint64_t one = 1;
    ssize_t rc = write(wake_fd_, &one, sizeof(one));
    (void)rc;
    return 0;
}

std::unique_ptr<Connection::Request> Connection::build_put(
    const std::vector<std::string>& keys, const std::vector<uint64_t>& offsets,
    uint32_t block_size, void* base_ptr, uint8_t priority, uint64_t trace_id,
    uint64_t trace_span) {
    if (keys.empty() || keys.size() != offsets.size()) return nullptr;
    uint64_t span = 0;
    for (uint64_t off : offsets) span = std::max(span, off + block_size);
    if (!base_registered(base_ptr, span)) {
        ITS_LOG_ERROR("put_batch: base pointer not inside a registered region");
        return nullptr;
    }
    auto req = std::make_unique<Request>();
    if (const ClientSeg* seg = find_seg(base_ptr, span)) {
        // One-RTT server-pull: the server memcpys straight out of the
        // mapped segment and commits; nothing else to do client-side.
        req->op = kOpPutFrom;
        SegBatchMeta m;
        m.block_size = block_size;
        m.seg_id = seg->id;
        m.keys = keys;
        m.priority = priority;
        m.trace_id = trace_id;
        m.trace_parent = trace_span;
        m.offsets.reserve(offsets.size());
        uint64_t base_off = static_cast<char*>(base_ptr) - seg->base;
        for (uint64_t off : offsets) m.offsets.push_back(base_off + off);
        m.encode(req->body);
        req->payload_on_wire = false;
    } else {
        bool shm = shm_ok_.load();
        req->op = shm ? kOpPutAlloc : kOpPutBatch;
        req->payload_on_wire = !shm;  // shm: blocks are memcpy'd after PutAlloc
        BatchMeta meta{block_size, keys, priority, trace_id, trace_span};
        meta.encode(req->body);
        req->tx_payload.reserve(keys.size());
        for (uint64_t off : offsets)
            req->tx_payload.push_back(iovec{static_cast<char*>(base_ptr) + off, block_size});
    }
    return req;
}

int Connection::put_batch_async(const std::vector<std::string>& keys,
                                const std::vector<uint64_t>& offsets, uint32_t block_size,
                                void* base_ptr, CompletionCb cb, void* ctx,
                                uint8_t priority, uint64_t trace_id, uint64_t trace_span) {
    auto req = build_put(keys, offsets, block_size, base_ptr, priority, trace_id,
                         trace_span);
    if (req == nullptr) return -1;
    req->cb = cb;
    req->ctx = ctx;
    return submit_any(std::move(req));
}

int Connection::put_batch(const std::vector<std::string>& keys,
                          const std::vector<uint64_t>& offsets, uint32_t block_size,
                          void* base_ptr, uint8_t priority, uint64_t trace_id,
                          uint64_t trace_span) {
    auto req = build_put(keys, offsets, block_size, base_ptr, priority, trace_id,
                         trace_span);
    if (req == nullptr) return -static_cast<int>(kStatusInvalidReq);
    uint32_t status = sync_roundtrip(std::move(req), nullptr, nullptr, nullptr);
    return status == kStatusOk ? 0 : -static_cast<int>(status);
}

std::unique_ptr<Connection::Request> Connection::build_get(
    const std::vector<std::string>& keys, const std::vector<uint64_t>& offsets,
    uint32_t block_size, void* base_ptr, uint8_t priority, uint64_t trace_id,
    uint64_t trace_span) {
    if (keys.empty() || keys.size() != offsets.size()) return nullptr;
    uint64_t span = 0;
    for (uint64_t off : offsets) span = std::max(span, off + block_size);
    if (!base_registered(base_ptr, span)) {
        ITS_LOG_ERROR("get_batch: base pointer not inside a registered region");
        return nullptr;
    }
    auto req = std::make_unique<Request>();
    if (const ClientSeg* seg = find_seg(base_ptr, span)) {
        // One-RTT server-push into the mapped segment; sizes land in-place.
        req->op = kOpGetInto;
        SegBatchMeta m;
        m.block_size = block_size;
        m.seg_id = seg->id;
        m.keys = keys;
        m.priority = priority;
        m.trace_id = trace_id;
        m.trace_parent = trace_span;
        m.offsets.reserve(offsets.size());
        uint64_t base_off = static_cast<char*>(base_ptr) - seg->base;
        for (uint64_t off : offsets) m.offsets.push_back(base_off + off);
        m.encode(req->body);
    } else {
        req->op = shm_ok_.load() ? kOpGetLoc : kOpGetBatch;
        BatchMeta meta{block_size, keys, priority, trace_id, trace_span};
        meta.encode(req->body);
        req->block_size = block_size;
        req->rx_addrs.reserve(keys.size());
        for (uint64_t off : offsets)
            req->rx_addrs.push_back(static_cast<char*>(base_ptr) + off);
    }
    return req;
}

int Connection::get_batch_async(const std::vector<std::string>& keys,
                                const std::vector<uint64_t>& offsets, uint32_t block_size,
                                void* base_ptr, CompletionCb cb, void* ctx,
                                uint8_t priority, uint64_t trace_id, uint64_t trace_span) {
    auto req = build_get(keys, offsets, block_size, base_ptr, priority, trace_id,
                         trace_span);
    if (req == nullptr) return -1;
    req->cb = cb;
    req->ctx = ctx;
    return submit_any(std::move(req));
}

int Connection::get_batch(const std::vector<std::string>& keys,
                          const std::vector<uint64_t>& offsets, uint32_t block_size,
                          void* base_ptr, uint8_t priority, uint64_t trace_id,
                          uint64_t trace_span) {
    auto req = build_get(keys, offsets, block_size, base_ptr, priority, trace_id,
                         trace_span);
    if (req == nullptr) return -static_cast<int>(kStatusInvalidReq);
    uint32_t status = sync_roundtrip(std::move(req), nullptr, nullptr, nullptr);
    return status == kStatusOk ? 0 : -static_cast<int>(status);
}

uint32_t Connection::sync_roundtrip(std::unique_ptr<Request> req,
                                    std::vector<uint8_t>* body_out, uint8_t** payload_out,
                                    size_t* payload_size_out, int timeout_ms) {
    auto state = std::make_shared<SyncState>();
    state->seg_op = req->op == kOpPutFrom || req->op == kOpGetInto;
    req->sync = state;
    auto fut = state->prom.get_future();
    if (submit_any(std::move(req)) != 0) return kStatusUnavailable;
    bool forever = false;
    if (timeout_ms < 0) {
        // Default deadline from config; config <= 0 opts into wait-forever.
        timeout_ms = config_.op_timeout_ms;
        forever = timeout_ms <= 0;
    }
    if (!forever) {
        if (fut.wait_for(std::chrono::milliseconds(timeout_ms)) !=
            std::future_status::ready) {
            // Abandon: the Request keeps the shared state alive, so a late
            // response completes harmlessly and FIFO matching stays intact.
            // The flag tells the reactor the caller's buffers are off-limits
            // from here on (see SyncState::abandoned) — but the reactor may
            // be INSIDE a buffer-touching region right now, so wait for
            // io_seq_ to go even before returning. Regions check the flag
            // after going odd, so once we observe even here no later region
            // can touch the buffers (Dekker pairing; regions are one
            // nonblocking syscall or a bounded memcpy loop, so this wait is
            // microseconds).
            state->abandoned.store(true);
            uint64_t s = io_seq_.load();
            if (s & 1) {
                // Wait for THIS section to exit (any change: a later section
                // entered after our store and so already sees the flag).
                while (io_seq_.load() == s) std::this_thread::yield();
            }
            if (state->seg_op) {
                // Segment-path op: the server reads/writes the mapped
                // segment directly, so an in-flight request cannot be
                // neutralized client-side. Poison the connection — the
                // reactor fails everything and the caller must reallocate
                // its alloc_shm_mr views (they never survive a dead
                // connection anyway).
                ITS_LOG_WARN("abandoned segment op; failing connection");
                poison_.store(true);
                uint64_t one = 1;
                ssize_t rc = ::write(wake_fd_, &one, sizeof(one));
                (void)rc;
                // Wait for the reactor to actually fail the connection so
                // the caller observes a DETERMINISTIC state (is_connected
                // false -> recovery paths take the reconnect branch, never
                // a racy retry of the poisoned op). Bounded: the reactor
                // checks poison_ every loop tick.
                for (int spin = 0; connected_.load() && spin < 4000; spin++)
                    std::this_thread::sleep_for(std::chrono::milliseconds(1));
            }
            return kStatusUnavailable;
        }
    } else {
        fut.wait();
    }
    if (body_out != nullptr) *body_out = std::move(state->body);
    if (payload_out != nullptr) {
        *payload_out = state->payload;
        *payload_size_out = state->payload_size;
        state->payload = nullptr;  // ownership to the caller
    }
    return state->status;
}

int Connection::tcp_put(const std::string& key, const void* data, size_t size) {
    auto req = std::make_unique<Request>();
    req->op = kOpTcpPut;
    TcpPutMeta meta{key, size};
    meta.encode(req->body);
    // Own a copy of the payload: sync ops can time out and be abandoned
    // while the reactor is still streaming the request — the iovec must not
    // reference caller memory the caller may free after the error returns.
    // The copy is a deliberate tax on this single-key convenience path;
    // bulk data belongs on the batched zero-copy API (register_mr +
    // put_batch_async), which keeps caller ownership until completion.
    req->owned_payload.assign(static_cast<const uint8_t*>(data),
                              static_cast<const uint8_t*>(data) + size);
    req->tx_payload.push_back(iovec{req->owned_payload.data(), size});
    uint32_t status = sync_roundtrip(std::move(req), nullptr, nullptr, nullptr);
    return status == kStatusOk ? 0 : -static_cast<int>(status);
}

int Connection::tcp_get(const std::string& key, uint8_t** out, size_t* out_size) {
    auto req = std::make_unique<Request>();
    req->op = kOpTcpGet;
    KeyMeta meta{key};
    meta.encode(req->body);
    req->alloc_rx = true;
    uint32_t status = sync_roundtrip(std::move(req), nullptr, out, out_size);
    return status == kStatusOk ? 0 : -static_cast<int>(status);
}

int Connection::check_exist(const std::string& key) {
    auto req = std::make_unique<Request>();
    req->op = kOpCheckExist;
    KeyMeta meta{key};
    meta.encode(req->body);
    std::vector<uint8_t> body;
    uint32_t status = sync_roundtrip(std::move(req), &body, nullptr, nullptr);
    if (status != kStatusOk || body.empty()) return -static_cast<int>(status);
    return body[0] != 0 ? 1 : 0;
}

int32_t Connection::get_match_last_index(const std::vector<std::string>& keys) {
    auto req = std::make_unique<Request>();
    req->op = kOpMatchLastIdx;
    KeyListMeta meta{keys};
    meta.encode(req->body);
    std::vector<uint8_t> body;
    uint32_t status = sync_roundtrip(std::move(req), &body, nullptr, nullptr);
    if (status != kStatusOk || body.size() < 4) return INT32_MIN;
    WireReader r(body.data(), body.size());
    return r.i32();
}

int64_t Connection::delete_keys(const std::vector<std::string>& keys) {
    auto req = std::make_unique<Request>();
    req->op = kOpDeleteKeys;
    KeyListMeta meta{keys};
    meta.encode(req->body);
    std::vector<uint8_t> body;
    uint32_t status = sync_roundtrip(std::move(req), &body, nullptr, nullptr);
    if (status != kStatusOk || body.size() < 4) return -static_cast<int64_t>(status);
    WireReader r(body.data(), body.size());
    return r.u32();
}

std::string Connection::stat_json() {
    auto req = std::make_unique<Request>();
    req->op = kOpStat;
    std::vector<uint8_t> body;
    uint32_t status = sync_roundtrip(std::move(req), &body, nullptr, nullptr);
    if (status != kStatusOk) return "";
    return std::string(body.begin(), body.end());
}

void Connection::set_completion_fd(int fd) { comp_fd_.store(fd); }

void Connection::completion_counters(uint64_t* pushed, uint64_t* signalled) const {
    if (pushed != nullptr) *pushed = comp_pushed_.load(std::memory_order_relaxed);
    if (signalled != nullptr) *signalled = comp_signalled_.load(std::memory_order_relaxed);
}

int Connection::drain_completions(uint64_t* tokens, int32_t* codes, int cap) {
    std::lock_guard<std::mutex> lock(ring_mu_);
    int n = static_cast<int>(std::min<size_t>(cap, ring_.size()));
    for (int i = 0; i < n; i++) {
        tokens[i] = ring_[i].first;
        codes[i] = ring_[i].second;
    }
    ring_.erase(ring_.begin(), ring_.begin() + n);
    return n;
}

void Connection::complete(std::unique_ptr<Request> req, int code, bool take_body) {
    if (req->sync != nullptr) {
        req->sync->status = static_cast<uint32_t>(code);
        // Only a request whose response was actually received may take
        // rbody_ — completions from fail_all or an abandoned-drop would
        // otherwise move out a DIFFERENT response's partially read body and
        // desync the stream.
        if (take_body) req->sync->body = std::move(rbody_);
        req->sync->payload = req->rx_buf;
        req->sync->payload_size = req->rx_buf_size;
        req->rx_buf = nullptr;
        req->sync->prom.set_value();
    } else if (req->cb != nullptr) {
        req->cb(req->ctx, code);
    } else if (comp_fd_.load() >= 0 && req->ctx != nullptr) {
        // Ring mode: push, then signal — the drainer reads the fd BEFORE
        // popping, so a push after its pop re-arms the fd and no completion
        // is ever stranded. Coalescing: the fd is written only when the
        // ring transitions empty -> non-empty. A non-empty ring means a
        // wakeup is already armed (or a drain is mid-flight, which clears
        // the fd first and then pops EVERYTHING under ring_mu_, so this
        // push is either seen by that drain or re-signalled by the next
        // empty-transition push) — completions landing in between, e.g. a
        // burst of small (<16KB) gets streaming back-to-back off one
        // socket, piggyback on the armed wakeup instead of paying one
        // eventfd syscall (and one loop wake) each.
        bool was_empty;
        {
            std::lock_guard<std::mutex> lock(ring_mu_);
            was_empty = ring_.empty();
            ring_.emplace_back(reinterpret_cast<uint64_t>(req->ctx), code);
        }
        comp_pushed_.fetch_add(1, std::memory_order_relaxed);
        if (was_empty) {
            comp_signalled_.fetch_add(1, std::memory_order_relaxed);
            uint64_t one = 1;
            ssize_t rc = ::write(comp_fd_.load(), &one, sizeof(one));
            (void)rc;
        }
    }
    if (req->rx_buf != nullptr) free(req->rx_buf);
}

void Connection::fail_all(int code) {
    {
        std::lock_guard<std::mutex> lock(submit_mu_);
        connected_.store(false);
        for (auto& req : submitted_) sendq_.push_back(std::move(req));
        submitted_.clear();
    }
    // Ring-posted ops: connected_ is false now, so no new descriptor can be
    // parked after this drain (try_ring_post checks under ring_mu_).
    std::vector<std::unique_ptr<Request>> ring_ops;
    {
        std::lock_guard<std::mutex> lock(dring_mu_);
        ring_ops.reserve(ring_inflight_.size() + group_reqs_.size());
        for (auto& [token, req] : ring_inflight_) ring_ops.push_back(std::move(req));
        ring_inflight_.clear();
        // An open batch group holds captured-but-unpublished ops; they die
        // with the connection like any other in-flight request.
        for (auto& req : group_reqs_) ring_ops.push_back(std::move(req));
        group_reqs_.clear();
        group_active_ = false;
    }
    for (auto& req : ring_ops) complete(std::move(req), code, /*take_body=*/false);
    while (!awaiting_.empty()) {
        auto req = std::move(awaiting_.front());
        awaiting_.pop_front();
        complete(std::move(req), code, /*take_body=*/false);
    }
    while (!sendq_.empty()) {
        auto req = std::move(sendq_.front());
        sendq_.pop_front();
        complete(std::move(req), code, /*take_body=*/false);
    }
}

bool Connection::flush_send() {
    if (poison_.load()) return false;  // abandoned segment op: stop sending
    static const std::vector<iovec> kNoPayload;
    while (!sendq_.empty()) {
        Request* req = sendq_.front().get();
        // Section covers the abandoned check AND the writev reading from
        // tx_payload: a timed-out waiter blocks until we exit it.
        IoSection sec(io_seq_);
        if (req->sync != nullptr && req->sync->abandoned.load()) {
            // Only a request whose WIRE payload gathers from caller memory
            // is dangerous to send. Everything else proceeds normally even
            // when abandoned — in particular a queued kOpPutCommit (body
            // only) MUST still go out, or the server-side ticket's pinned
            // pool blocks leak; late responses are drained/completed into
            // the shared SyncState.
            bool refs_caller = req->payload_on_wire && !req->tx_payload.empty() &&
                               req->owned_payload.empty();
            if (refs_caller && req->sent == 0) {
                // Never reached the wire: drop it whole — the server never
                // saw it, so FIFO response matching is unaffected and there
                // is no server-side state to clean up.
                auto dead = std::move(sendq_.front());
                sendq_.pop_front();
                complete(std::move(dead), static_cast<int>(kStatusUnavailable),
                         /*take_body=*/false);
                continue;
            }
            if (refs_caller && req->sent < req->send_total) {
                // Half-streamed from caller memory the caller may have freed
                // after the timeout. Abandoning mid-frame would desync the
                // protocol; the only safe move is to fail the connection.
                ITS_LOG_ERROR("abandoned sync op mid-stream; failing connection");
                return false;
            }
        }
        iovec iov[64];
        const std::vector<iovec>& wire_payload =
            req->payload_on_wire ? req->tx_payload : kNoPayload;
        size_t niov = build_send_iov(&req->hdr, sizeof(ReqHeader), req->body, wire_payload,
                                     req->sent, iov, 64);
        ssize_t r = writev_nosignal(fd_, iov, static_cast<int>(niov));
        if (r < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                epoll_event ev{};
                ev.events = EPOLLIN | EPOLLOUT;
                ev.data.fd = fd_;
                epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, fd_, &ev);
                return true;
            }
            return false;
        }
        req->sent += static_cast<size_t>(r);
        if (req->sent == req->send_total) {
            if (req->no_response) {
                sendq_.pop_front();  // fire-and-forget (release)
            } else {
                awaiting_.push_back(std::move(sendq_.front()));
                sendq_.pop_front();
            }
        }
    }
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, fd_, &ev);
    return true;
}

bool Connection::read_ready() {
    if (poison_.load()) return false;
    while (true) {
        if (!resp_in_progress_) {
            ssize_t r = read(fd_, reinterpret_cast<char*>(&rhdr_) + rhdr_got_,
                             sizeof(RespHeader) - rhdr_got_);
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            rhdr_got_ += static_cast<size_t>(r);
            if (rhdr_got_ < sizeof(RespHeader)) continue;
            if (rhdr_.status == kStatusRingEvent) {
                // Unsolicited completion-ring doorbell: not matched to an
                // in-flight request — drain the CQ and keep reading.
                if (rhdr_.body_size != 0 || rhdr_.payload_size != 0) {
                    ITS_LOG_ERROR("protocol error: ring doorbell with body");
                    return false;
                }
                rhdr_got_ = 0;
                if (!drain_cq()) return false;
                continue;
            }
            if (awaiting_.empty() || rhdr_.body_size > kMaxBodySize) {
                ITS_LOG_ERROR("protocol error: unexpected response");
                return false;
            }
            if (rhdr_.status < 100 || rhdr_.status > 599) {
                // HTTP-like status range (protocol.h). Anything else is a
                // desynced or hostile stream — fail the connection rather
                // than complete ops with a bogus code (a status of 0 would
                // collide with "success" returns up the stack).
                ITS_LOG_ERROR("protocol error: invalid status %u", rhdr_.status);
                return false;
            }
            rbody_.resize(rhdr_.body_size);
            rbody_got_ = 0;
            resp_in_progress_ = true;
            rx_setup_done_ = false;
        }

        Request* req = awaiting_.front().get();
        if (rbody_got_ < rbody_.size()) {
            ssize_t r = read(fd_, rbody_.data() + rbody_got_, rbody_.size() - rbody_got_);
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            rbody_got_ += static_cast<size_t>(r);
            if (rbody_got_ < rbody_.size()) continue;
        }
        if (!rx_setup_done_) {
            // Body complete (possibly empty): set up payload reception once.
            rx_setup_done_ = true;
            rx_iov_.clear();
            rx_cur_.reset();
            rx_discard_ = 0;
            rx_failed_ = false;
            if (rhdr_.payload_size > 0) {
                if (req->sync != nullptr && req->sync->abandoned.load()) {
                    // The waiter timed out; its buffers may be gone. Drain.
                    rx_discard_ = rhdr_.payload_size;
                } else if (req->op == kOpGetBatch && rhdr_.status == kStatusOk) {
                    WireReader rd(rbody_.data(), rbody_.size());
                    uint32_t n = rd.u32();
                    if (n != req->rx_addrs.size()) {
                        ITS_LOG_ERROR("get_batch: size list mismatch");
                        return false;
                    }
                    for (uint32_t i = 0; i < n; i++) {
                        uint32_t sz = rd.u32();
                        // A stored block larger than the caller's slot must
                        // not scatter past rx_addrs[i]: fail the op and
                        // drain the payload instead of overflowing.
                        if (sz > req->block_size) {
                            ITS_LOG_ERROR(
                                "get_batch: stored block (%u) exceeds requested "
                                "block_size (%u)", sz, req->block_size);
                            rx_iov_.clear();
                            rx_discard_ = rhdr_.payload_size;
                            rx_failed_ = true;
                            break;
                        }
                        rx_iov_.push_back(iovec{req->rx_addrs[i], sz});
                    }
                } else if (req->alloc_rx && rhdr_.status == kStatusOk) {
                    req->rx_buf = static_cast<uint8_t*>(malloc(rhdr_.payload_size));
                    req->rx_buf_size = rhdr_.payload_size;
                    rx_iov_.push_back(iovec{req->rx_buf, rhdr_.payload_size});
                } else {
                    rx_discard_ = rhdr_.payload_size;
                }
            }
        }

        // Payload phase.
        if (rx_discard_ > 0) {
            char scratch[64 << 10];
            ssize_t r = read(fd_, scratch, std::min<uint64_t>(rx_discard_, sizeof(scratch)));
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            rx_discard_ -= static_cast<uint64_t>(r);
            if (rx_discard_ > 0) continue;
        } else if (!rx_cur_.done(rx_iov_)) {
            // Section covers the abandoned check AND the readv scattering
            // into rx_addrs: a timed-out waiter blocks until we exit it.
            IoSection sec(io_seq_);
            if (req->sync != nullptr && req->sync->abandoned.load()) {
                // Timed out mid-scatter: stop touching the caller's buffers
                // and drain the rest of the payload into scratch.
                rx_discard_ = rx_cur_.remaining(rx_iov_);
                rx_iov_.clear();
                rx_cur_.reset();
                continue;
            }
            iovec iov[64];
            size_t niov = rx_cur_.fill(rx_iov_, iov, 64);
            ssize_t r = readv(fd_, iov, static_cast<int>(niov));
            if (r == 0) return false;
            if (r < 0) return errno == EAGAIN || errno == EWOULDBLOCK;
            rx_cur_.advance(rx_iov_, static_cast<size_t>(r));
            if (!rx_cur_.done(rx_iov_)) continue;
        }

        // Response fully received.
        auto done = std::move(awaiting_.front());
        awaiting_.pop_front();
        resp_in_progress_ = false;
        rhdr_got_ = 0;
        if (rx_failed_) {
            rx_failed_ = false;
            complete(std::move(done), static_cast<int>(kStatusInternal),
                     /*take_body=*/true);
        } else if (done->op == kOpPutAlloc || done->op == kOpGetLoc) {
            auto requeue = shm_phase(std::move(done), rhdr_.status);
            if (requeue != nullptr) sendq_.push_back(std::move(requeue));
            if (!sendq_.empty() && !flush_send()) return false;
        } else {
            complete(std::move(done), static_cast<int>(rhdr_.status),
                     /*take_body=*/true);
        }
    }
}

// Handle a shm fast-path response on the reactor thread: memcpy payload
// between user memory and the mapped pools, then either requeue the request
// as a commit (put) or release the server-side pins and complete (get).
std::unique_ptr<Connection::Request> Connection::shm_phase(std::unique_ptr<Request> req,
                                                           uint32_t status) {
    bool put = req->op == kOpPutAlloc;
    // Convert back to the socket-path op: the request body (BatchMeta) and
    // payload endpoints are identical, so the op byte is the only change.
    auto fall_back = [this, put](std::unique_ptr<Request> r) {
        shm_ok_.store(false);
        ITS_LOG_WARN("shm fast path degraded; retrying over the socket");
        r->op = put ? kOpPutBatch : kOpGetBatch;
        r->payload_on_wire = true;
        r->prime();
        return r;
    };
    if (status == kStatusRetry) {
        // Server placed (or stored) the blocks in a pool that is not shm-
        // mappable (e.g. /dev/shm quota forced an anonymous extend pool).
        return fall_back(std::move(req));
    }
    if (status != kStatusOk) {
        complete(std::move(req), static_cast<int>(status), /*take_body=*/true);
        return nullptr;
    }
    ShmLocResp resp;
    try {
        resp = ShmLocResp::decode(rbody_.data(), rbody_.size());
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("shm response parse failed: %s", e.what());
        complete(std::move(req), static_cast<int>(kStatusInternal),
                 /*take_body=*/true);
        return nullptr;
    }
    size_t n = resp.locs.size();
    bool ok = put ? n == req->tx_payload.size() : n == req->rx_addrs.size();
    std::vector<char*> at(n);
    for (size_t i = 0; ok && i < n; i++) {
        const ShmLoc& l = resp.locs[i];
        char* base = nullptr;
        size_t mapped_size = 0;
        {
            std::lock_guard<std::mutex> lock(shm_mu_);
            auto it = shm_pools_.find(l.pool_id);
            if (it != shm_pools_.end()) {
                base = it->second.base;
                mapped_size = it->second.size;
            }
        }
        if (base == nullptr) {
            // Auto-extended pool: map on demand from the embedded directory.
            for (const auto& p : resp.pools) {
                if (p.pool_id == l.pool_id) {
                    base = map_pool(p.pool_id, p.name, p.size);
                    mapped_size = p.size;
                    break;
                }
            }
        }
        // On gets, a stored block larger than the caller's slot must not
        // overflow rx_addrs[i]: that is a size-contract violation, not a
        // mapping problem — fail the op (no socket retry: that path would
        // face the same oversized payload).
        if (!put && l.size > req->block_size) {
            ITS_LOG_ERROR("shm get: stored block (%u) exceeds requested block_size (%u)",
                          l.size, req->block_size);
            queue_release(resp.ticket);
            complete(std::move(req), static_cast<int>(kStatusInternal),
                 /*take_body=*/true);
            return nullptr;
        }
        // Bounds-check against the mapping: a malformed location must not
        // drive memcpy out of the pool (the socket path bounds everything
        // through validated iovecs; this is the shm equivalent).
        size_t span = put ? req->tx_payload[i].iov_len : static_cast<size_t>(l.size);
        if (base == nullptr || l.offset > mapped_size || span > mapped_size - l.offset) {
            ok = false;
            break;
        }
        at[i] = base + l.offset;
    }
    if (!ok) {
        queue_release(resp.ticket);  // abort: drop the server-side ticket
        return fall_back(std::move(req));
    }
    // Section covers the abandoned check AND the memcpys against caller
    // memory: a timed-out waiter blocks until we exit it (bounded loop).
    IoSection sec(io_seq_);
    if (req->sync != nullptr && req->sync->abandoned.load()) {
        // Timed-out waiter: tx_payload/rx_addrs point at memory the caller
        // may have freed — abort the ticket instead of memcpy'ing.
        queue_release(resp.ticket);
        complete(std::move(req), static_cast<int>(kStatusUnavailable),
                 /*take_body=*/true);
        return nullptr;
    }
    if (put) {
        for (size_t i = 0; i < n; i++)
            memcpy(at[i], req->tx_payload[i].iov_base, req->tx_payload[i].iov_len);
        // Phase 2: publish the keys (commit-on-copy-complete).
        req->op = kOpPutCommit;
        req->body.clear();
        TicketMeta{resp.ticket}.encode(req->body);
        req->tx_payload.clear();
        req->prime();
        return req;
    }
    for (size_t i = 0; i < n; i++) memcpy(req->rx_addrs[i], at[i], resp.locs[i].size);
    queue_release(resp.ticket);
    complete(std::move(req), static_cast<int>(kStatusOk), /*take_body=*/true);
    return nullptr;
}

void Connection::queue_release(uint64_t ticket) {
    auto rel = std::make_unique<Request>();
    rel->op = kOpRelease;
    TicketMeta{ticket}.encode(rel->body);
    rel->no_response = true;
    rel->prime();
    sendq_.push_back(std::move(rel));
}

void Connection::reactor() {
    constexpr int kMaxEvents = 8;
    epoll_event events[kMaxEvents];
    bool ok = true;
    auto dispatch = [&](int n) {
        for (int i = 0; i < n && ok; i++) {
            int fd = events[i].data.fd;
            if (fd == wake_fd_) {
                uint64_t buf;
                while (read(wake_fd_, &buf, sizeof(buf)) > 0) {
                }
                {
                    std::lock_guard<std::mutex> lock(submit_mu_);
                    for (auto& req : submitted_) sendq_.push_back(std::move(req));
                    submitted_.clear();
                }
                ok = flush_send();
            } else {
                if (events[i].events & (EPOLLHUP | EPOLLERR)) {
                    ok = false;
                    break;
                }
                if (events[i].events & EPOLLOUT) ok = flush_send();
                if (ok && (events[i].events & EPOLLIN)) ok = read_ready();
            }
        }
    };
    while (ok && !stop_.load(std::memory_order_relaxed)) {
        if (poison_.load()) break;  // abandoned segment op: fail everything
        int timeout = 200;
        if (ring_ok_.load(std::memory_order_acquire)) {
            if (!drain_cq()) break;
            // Adaptive poll-then-park (docs/descriptor_ring.md): with ring
            // ops in flight and completions arriving on a fast cadence,
            // busy-poll the CQ for ~2x the smoothed inter-CQE gap before
            // arming the doorbell — a hit completes the op with no park, no
            // doorbell frame, no epoll wake. Socket/wake traffic is served
            // inside the window (zero-timeout epoll), so posting and
            // payload streaming are never starved by the spin. An idle ring
            // (nothing in flight) or a slow cadence yields a zero budget:
            // straight to the parked doze, zero CPU.
            bool inflight;
            {
                std::lock_guard<std::mutex> lock(dring_mu_);
                inflight = !ring_inflight_.empty();
            }
            if (inflight) {
                uint64_t budget = ring_poll_budget(ring_gap_ewma_us_);
                bool hit = false;
                if (budget != 0) {
                    uint64_t deadline = now_us() + budget;
                    while (ok && !stop_.load(std::memory_order_relaxed) &&
                           !poison_.load()) {
                        if (ring_load_acq(&dring_->view.ctrl->cq_tail) !=
                            ring_cq_seq_) {
                            hit = true;
                            break;
                        }
                        int pn = epoll_wait(epoll_fd_, events, kMaxEvents, 0);
                        if (pn > 0) dispatch(pn);
                        if (now_us() >= deadline) break;
                        // Mandatory on a shared core: the server thread we
                        // are polling against needs cycles to publish.
                        std::this_thread::yield();
                    }
                }
                if (!ok) break;
                if (hit) {
                    ring_poll_hits_.fetch_add(1, std::memory_order_relaxed);
                    if (!drain_cq()) break;
                    continue;
                }
                ring_poll_arms_.fetch_add(1, std::memory_order_relaxed);
            }
            // Park-then-recheck (Dekker pairing with the server's CQE
            // publish + flag read): either we see the new tail here, or the
            // server sees cli_waiting and sends a doorbell frame.
            ring_flag_park(&dring_->view.ctrl->cli_waiting);
            ring_fence();
            if (ring_load_acq(&dring_->view.ctrl->cq_tail) != ring_cq_seq_) {
                ring_flag_clear(&dring_->view.ctrl->cli_waiting);
                if (!drain_cq()) break;
                // The recheck hit, so the flag is DOWN: a CQE published
                // while we slept would send no doorbell. Poll instead of
                // blocking — the next loop iteration re-parks properly
                // (the server's loop() applies the same discipline).
                timeout = 0;
            }
        }
        int n = epoll_wait(epoll_fd_, events, kMaxEvents, timeout);
        if (ring_ok_.load(std::memory_order_acquire)) {
            ring_flag_clear(&dring_->view.ctrl->cli_waiting);
            if (!drain_cq()) break;
        }
        if (n < 0) {
            if (errno == EINTR) continue;
            break;
        }
        dispatch(n);
    }
    fail_all(kStatusUnavailable);
}

}  // namespace its
