// C API exported to Python over ctypes.
//
// Replaces the reference's pybind11 module (/root/reference/src/pybind.cpp) —
// pybind11 is not available in this environment, and ctypes gives the same
// properties for free: the GIL is released for the duration of every foreign
// call, and C→Python callbacks (used for async op completions, the analogue of
// pybind's callback bridging at pybind.cpp:66-80) re-acquire it automatically.
// Key lists cross the boundary as a single packed blob of (u16 len, bytes)
// entries — one memcpy on the Python side instead of per-key object traffic.
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "its/client.h"
#include "its/kvstore.h"
#include "its/log.h"
#include "its/mempool.h"
#include "its/protocol.h"
#include "its/server.h"

using its::ClientConfig;
using its::Connection;
using its::MM;
using its::Server;
using its::ServerConfig;

namespace {

std::vector<std::string> parse_keys_blob(const uint8_t* blob, uint64_t blob_len,
                                         uint32_t nkeys) {
    its::WireReader r(blob, blob_len);
    std::vector<std::string> keys;
    keys.reserve(nkeys);
    for (uint32_t i = 0; i < nkeys; i++) keys.push_back(r.str());
    return keys;
}

int copy_out(const std::string& s, char* buf, int buf_len) {
    if (buf_len <= 0) return -1;
    size_t n = std::min(s.size(), static_cast<size_t>(buf_len - 1));
    memcpy(buf, s.data(), n);
    buf[n] = '\0';
    return static_cast<int>(n);
}

// Exceptions (oversized keys from WireWriter::str, malformed blobs from
// WireReader) must not unwind through the FFI boundary — that is UB under
// libffi and would abort the Python process. Each guarded call maps them to
// an error return instead.
template <typename F>
static auto guarded(F&& f, decltype(f()) err) -> decltype(f()) {
    try {
        return f();
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("native call failed: %s", e.what());
        return err;
    }
}

}  // namespace

extern "C" {

void its_install_crash_handler() { its::install_crash_handler(); }

// ---- logging ----
void its_set_log_level(int level) { its::set_log_level(static_cast<its::LogLevel>(level)); }
void its_set_log_sink(its::LogSink sink) { its::set_log_sink(sink); }
void its_log(int level, const char* msg) {
    its::log_msg(static_cast<its::LogLevel>(level), "%s", msg);
}

// ---- server ----
void* its_server_create(const char* bind_addr, int port, uint64_t prealloc_bytes,
                        uint64_t block_bytes, int auto_increase, uint64_t extend_bytes,
                        int pin, double evict_min, double evict_max, int enable_shm,
                        int pacing_rate_mbps, const char* spill_dir,
                        uint64_t spill_bytes) {
    ServerConfig cfg;
    cfg.bind_addr = bind_addr;
    cfg.service_port = port;
    cfg.prealloc_bytes = prealloc_bytes;
    cfg.block_size = block_bytes;
    cfg.auto_increase = auto_increase != 0;
    cfg.extend_pool_bytes = extend_bytes;
    cfg.pin_memory = pin != 0;
    cfg.evict_min_ratio = evict_min;
    cfg.evict_max_ratio = evict_max;
    cfg.enable_shm = enable_shm != 0;
    cfg.pacing_rate_mbps = pacing_rate_mbps > 0 ? static_cast<uint32_t>(pacing_rate_mbps) : 0;
    cfg.spill_dir = spill_dir != nullptr ? spill_dir : "";
    cfg.spill_bytes = spill_bytes;
    try {
        return new Server(cfg);
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("server create failed: %s", e.what());
        return nullptr;
    }
}
int its_server_start(void* s) { return static_cast<Server*>(s)->start() ? 0 : -1; }
void its_server_stop(void* s) { static_cast<Server*>(s)->stop(); }
void its_server_destroy(void* s) { delete static_cast<Server*>(s); }
int its_server_port(void* s) { return static_cast<Server*>(s)->port(); }
uint64_t its_server_kvmap_len(void* s) { return static_cast<Server*>(s)->kvmap_len(); }
uint64_t its_server_purge(void* s) { return static_cast<Server*>(s)->purge(); }
uint64_t its_server_evict(void* s, double min_r, double max_r) {
    return static_cast<Server*>(s)->evict(min_r, max_r);
}
double its_server_usage(void* s) { return static_cast<Server*>(s)->usage(); }
int its_server_stats_json(void* s, char* buf, int buf_len) {
    return copy_out(static_cast<Server*>(s)->stats_json(), buf, buf_len);
}

// ---- client ----
// ``enable_ring``/``ring_slots``: descriptor-ring data plane
// (docs/descriptor_ring.md) — batched segment ops post as shared-memory
// descriptors instead of per-op socket writes when the shm fast path is up.
// ring_slots 0 = default (its::kRingSqSlots).
void* its_conn_create(const char* host, int port, int timeout_ms, int enable_shm,
                      int op_timeout_ms, int pacing_rate_mbps, int enable_ring,
                      int ring_slots) {
    ClientConfig cfg;
    cfg.host = host;
    cfg.port = port;
    cfg.connect_timeout_ms = timeout_ms;
    cfg.op_timeout_ms = op_timeout_ms;
    cfg.enable_shm = enable_shm != 0;
    cfg.pacing_rate_mbps = pacing_rate_mbps > 0 ? static_cast<uint32_t>(pacing_rate_mbps) : 0;
    cfg.enable_ring = enable_ring != 0;
    cfg.ring_slots = ring_slots > 0 ? static_cast<uint32_t>(ring_slots) : 0;
    return new Connection(cfg);
}
int its_conn_connect(void* c) { return static_cast<Connection*>(c)->connect(); }
int its_conn_shm_active(void* c) { return static_cast<Connection*>(c)->shm_active() ? 1 : 0; }
int its_conn_ring_active(void* c) { return static_cast<Connection*>(c)->ring_active() ? 1 : 0; }
// Shm name of the connection's descriptor-ring segment (empty when
// inactive): the introspection hook the torn-descriptor tests use to map
// and tamper with the ring from outside the client.
int its_conn_ring_name(void* c, char* buf, int buf_len) {
    return copy_out(static_cast<Connection*>(c)->ring_name(), buf, buf_len);
}
// Client half of the ring ledger (lib.InfinityConnection.ring_stats):
// descriptors posted, submission doorbells sent (doze transitions only),
// ring-full + oversized-meta socket fallbacks, completions consumed.
void its_conn_ring_counters(void* c, uint64_t* posted, uint64_t* doorbells,
                            uint64_t* full_fallbacks, uint64_t* meta_fallbacks,
                            uint64_t* completions) {
    static_cast<Connection*>(c)->ring_counters(posted, doorbells, full_fallbacks,
                                               meta_fallbacks, completions);
}
// PR 16 mechanism counters: batch slots published / ops packed into them,
// reactor poll-window hits vs doorbell arms (lib.ring_stats extension —
// its_conn_ring_counters keeps its 5-value signature for ABI stability).
void its_conn_ring_poll_counters(void* c, uint64_t* batch_slots, uint64_t* batch_ops,
                                 uint64_t* poll_hits, uint64_t* poll_arms) {
    static_cast<Connection*>(c)->ring_poll_counters(batch_slots, batch_ops, poll_hits,
                                                    poll_arms);
}
// Multi-op batch grouping: the asyncio bridge brackets one event-loop
// tick's ring posts between begin/end so a whole FetchCoalescer flush
// publishes as one batch slot (docs/descriptor_ring.md). Safe no-ops when
// the ring is down.
void its_conn_ring_group_begin(void* c) {
    static_cast<Connection*>(c)->ring_group_begin();
}
void its_conn_ring_group_end(void* c) { static_cast<Connection*>(c)->ring_group_end(); }
void its_conn_close(void* c) { static_cast<Connection*>(c)->close(); }
void its_conn_destroy(void* c) { delete static_cast<Connection*>(c); }
int its_conn_connected(void* c) { return static_cast<Connection*>(c)->connected() ? 1 : 0; }
int its_conn_unregister_mr(void* c, void* ptr) {
    return static_cast<Connection*>(c)->unregister_mr(ptr);
}
int its_conn_register_mr(void* c, void* ptr, uint64_t size) {
    return static_cast<Connection*>(c)->register_mr(ptr, size);
}
// Returns the mapped base of a server-shared staging segment (one-RTT data
// plane), or NULL when the server is remote/shm-less.
void* its_conn_alloc_shm_mr(void* c, uint64_t size) {
    return static_cast<Connection*>(c)->alloc_shm_mr(size);
}

// Event-fd completion ring: the caller owns fd (never closed here); async
// batched ops submitted with cb=NULL, ctx=token complete into the ring.
void its_conn_set_completion_fd(void* c, int fd) {
    static_cast<Connection*>(c)->set_completion_fd(fd);
}
int its_conn_drain_completions(void* c, uint64_t* tokens, int32_t* codes, int cap) {
    return static_cast<Connection*>(c)->drain_completions(tokens, codes, cap);
}
// Wakeup-coalescing counters: ring pushes vs eventfd writes (the fd is
// written only on empty->non-empty transitions; see Connection::complete).
void its_conn_completion_counters(void* c, uint64_t* pushed, uint64_t* signalled) {
    static_cast<Connection*>(c)->completion_counters(pushed, signalled);
}

// ``priority``: QoS class tag (its::Priority) — 0 foreground (default
// scheduling, wire bytes unchanged), 1 background (yields to foreground in
// the server's two-level slice scheduler; see docs/qos.md).
// ``trace_id``/``trace_span``: per-op trace context (docs/observability.md)
// — 0/0 (the default/untraced case) adds ZERO wire bytes; non-zero rides
// the trailing trace extension and the server stamps recv/slice/done ticks
// for the op into its trace ring (stats_json "trace").
int its_conn_put_batch(void* c, const uint8_t* keys_blob, uint64_t blob_len, uint32_t nkeys,
                       const uint64_t* offsets, uint32_t block_size, void* base_ptr,
                       its::CompletionCb cb, void* ctx, int priority,
                       uint64_t trace_id, uint64_t trace_span) {
    return guarded([&]() -> int {
        auto keys = parse_keys_blob(keys_blob, blob_len, nkeys);
        std::vector<uint64_t> offs(offsets, offsets + nkeys);
        return static_cast<Connection*>(c)->put_batch_async(keys, offs, block_size, base_ptr,
                                                            cb, ctx,
                                                            static_cast<uint8_t>(priority),
                                                            trace_id, trace_span);
    }, -1);
}
int its_conn_get_batch(void* c, const uint8_t* keys_blob, uint64_t blob_len, uint32_t nkeys,
                       const uint64_t* offsets, uint32_t block_size, void* base_ptr,
                       its::CompletionCb cb, void* ctx, int priority,
                       uint64_t trace_id, uint64_t trace_span) {
    return guarded([&]() -> int {
        auto keys = parse_keys_blob(keys_blob, blob_len, nkeys);
        std::vector<uint64_t> offs(offsets, offsets + nkeys);
        return static_cast<Connection*>(c)->get_batch_async(keys, offs, block_size, base_ptr,
                                                            cb, ctx,
                                                            static_cast<uint8_t>(priority),
                                                            trace_id, trace_span);
    }, -1);
}
// Sync batched ops: calling thread blocks on completion (no asyncio hop) —
// the low-latency path for small fetches. Returns 0 or -status.
int its_conn_put_batch_sync(void* c, const uint8_t* keys_blob, uint64_t blob_len,
                            uint32_t nkeys, const uint64_t* offsets, uint32_t block_size,
                            void* base_ptr, int priority,
                            uint64_t trace_id, uint64_t trace_span) {
    return guarded([&]() -> int {
        auto keys = parse_keys_blob(keys_blob, blob_len, nkeys);
        std::vector<uint64_t> offs(offsets, offsets + nkeys);
        return static_cast<Connection*>(c)->put_batch(keys, offs, block_size, base_ptr,
                                                      static_cast<uint8_t>(priority),
                                                      trace_id, trace_span);
    }, -static_cast<int>(its::kStatusInvalidReq));
}
int its_conn_get_batch_sync(void* c, const uint8_t* keys_blob, uint64_t blob_len,
                            uint32_t nkeys, const uint64_t* offsets, uint32_t block_size,
                            void* base_ptr, int priority,
                            uint64_t trace_id, uint64_t trace_span) {
    return guarded([&]() -> int {
        auto keys = parse_keys_blob(keys_blob, blob_len, nkeys);
        std::vector<uint64_t> offs(offsets, offsets + nkeys);
        return static_cast<Connection*>(c)->get_batch(keys, offs, block_size, base_ptr,
                                                      static_cast<uint8_t>(priority),
                                                      trace_id, trace_span);
    }, -static_cast<int>(its::kStatusInvalidReq));
}
int its_conn_tcp_put(void* c, const char* key, const void* data, uint64_t size) {
    return guarded(
        [&]() -> int { return static_cast<Connection*>(c)->tcp_put(key, data, size); },
        -static_cast<int>(its::kStatusInvalidReq));
}
int its_conn_tcp_get(void* c, const char* key, uint8_t** out, uint64_t* out_size) {
    return guarded(
        [&]() -> int {
            size_t sz = 0;
            int rc = static_cast<Connection*>(c)->tcp_get(key, out, &sz);
            *out_size = sz;
            return rc;
        },
        -static_cast<int>(its::kStatusInvalidReq));
}
void its_free(void* p) { free(p); }
int its_conn_check_exist(void* c, const char* key) {
    return guarded([&]() -> int { return static_cast<Connection*>(c)->check_exist(key); },
                   -static_cast<int>(its::kStatusInvalidReq));
}
int32_t its_conn_match_last_index(void* c, const uint8_t* keys_blob, uint64_t blob_len,
                                  uint32_t nkeys) {
    return guarded(
        [&]() -> int32_t {
            return static_cast<Connection*>(c)->get_match_last_index(
                parse_keys_blob(keys_blob, blob_len, nkeys));
        },
        INT32_MIN);
}
int64_t its_conn_delete_keys(void* c, const uint8_t* keys_blob, uint64_t blob_len,
                             uint32_t nkeys) {
    return guarded(
        [&]() -> int64_t {
            return static_cast<Connection*>(c)->delete_keys(
                parse_keys_blob(keys_blob, blob_len, nkeys));
        },
        -static_cast<int64_t>(its::kStatusInvalidReq));
}
int its_conn_stat_json(void* c, char* buf, int buf_len) {
    return copy_out(static_cast<Connection*>(c)->stat_json(), buf, buf_len);
}

// ---- mempool (unit-test surface; the reference has no allocator tests at
// all — SURVEY.md §4 flags that as its weakest subsystem) ----
void* its_mm_create(uint64_t pool_bytes, uint64_t block_bytes, int pin) {
    try {
        return new MM(pool_bytes, block_bytes, pin != 0);
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("mm create failed: %s", e.what());
        return nullptr;
    }
}
void its_mm_destroy(void* mm) { delete static_cast<MM*>(mm); }
int its_mm_allocate(void* mm, uint64_t size, uint32_t n, void** out_ptrs) {
    std::vector<its::Lease> leases;
    if (!static_cast<MM*>(mm)->allocate(size, n, nullptr, &leases)) return -1;
    for (uint32_t i = 0; i < n; i++) out_ptrs[i] = leases[i].ptr;
    return 0;
}
void its_mm_deallocate(void* mm, void* ptr, uint64_t size) {
    static_cast<MM*>(mm)->deallocate(ptr, size);
}
double its_mm_usage(void* mm) { return static_cast<MM*>(mm)->usage(); }
int its_mm_extend(void* mm, uint64_t pool_bytes) {
    return static_cast<MM*>(mm)->extend(pool_bytes) ? 0 : -1;
}
uint64_t its_mm_total_bytes(void* mm) { return static_cast<MM*>(mm)->total_bytes(); }
uint64_t its_mm_used_bytes(void* mm) { return static_cast<MM*>(mm)->used_bytes(); }
int its_mm_pinned(void* mm) { return static_cast<MM*>(mm)->pinned() ? 1 : 0; }

}  // extern "C"
