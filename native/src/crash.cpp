// Fatal-signal stacktrace handler (reference utils.cpp:94-101 + 216-223
// installs boost::stacktrace printers for SIGSEGV/ABRT/BUS/FPE/ILL on both
// server and client; we use glibc backtrace() — async-signal-unsafe in
// theory, as is boost's, but this only runs on the way down).
#include <execinfo.h>
#include <signal.h>
#include <string.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <initializer_list>

#include "its/mempool.h"

namespace its {

namespace {

void crash_handler(int sig) {
    void* frames[64];
    int n = backtrace(frames, 64);
    dprintf(STDERR_FILENO, "\n[infinistore-tpu] fatal signal %d (%s); backtrace:\n", sig,
            strsignal(sig));
    backtrace_symbols_fd(frames, n, STDERR_FILENO);
    // Unlink live shm pool segments so tmpfs pages don't outlive the process
    // (async-signal-safe: walks a static table, calls shm_unlink only).
    shm_registry_unlink_all();
    // Restore default and re-raise so the exit status reflects the signal.
    signal(sig, SIG_DFL);
    raise(sig);
}

}  // namespace

void install_crash_handler() {
    static bool installed = false;
    if (installed) return;
    installed = true;
    // Warm up backtrace(): the first call dlopen()s libgcc_s, which mallocs —
    // doing that lazily inside the handler can deadlock if the crash happened
    // under malloc's arena lock.
    void* warm[1];
    backtrace(warm, 1);
    for (int sig : {SIGSEGV, SIGABRT, SIGBUS, SIGFPE, SIGILL}) {
        struct sigaction old{};
        sigaction(sig, nullptr, &old);
        // Don't clobber handlers the embedding application (faulthandler,
        // absl, JAX) already installed; only claim unhandled signals.
        if (old.sa_handler != SIG_DFL || (old.sa_flags & SA_SIGINFO)) continue;
        struct sigaction sa{};
        sa.sa_handler = crash_handler;
        sigemptyset(&sa.sa_mask);
        sa.sa_flags = SA_RESETHAND;
        sigaction(sig, &sa, nullptr);
    }
}

}  // namespace its
