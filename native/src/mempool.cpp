#include "its/mempool.h"

#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <sys/file.h>
#include <strings.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <stdexcept>

#include "its/log.h"

namespace its {

namespace {
constexpr size_t kAlignment = 4096;

bool is_pow2(size_t v) { return v != 0 && (v & (v - 1)) == 0; }

// Registry of live shm segment names for crash-time cleanup. Fixed-size slots
// with plain char arrays: the fatal-signal handler walks it without taking
// locks or touching the heap.
constexpr size_t kMaxSegments = 512;
constexpr size_t kMaxName = 96;
char g_segments[kMaxSegments][kMaxName];
std::mutex g_segments_mu;
}  // namespace

void shm_registry_add(const char* name) {
    std::lock_guard<std::mutex> lock(g_segments_mu);
    for (auto& slot : g_segments) {
        if (slot[0] == '\0') {
            snprintf(slot, kMaxName, "%s", name);
            return;
        }
    }
    ITS_LOG_WARN("shm registry full; %s will leak if the process crashes", name);
}

void shm_registry_remove(const char* name) {
    std::lock_guard<std::mutex> lock(g_segments_mu);
    for (auto& slot : g_segments) {
        if (strncmp(slot, name, kMaxName) == 0) {
            slot[0] = '\0';
            return;
        }
    }
}

void shm_registry_unlink_all() {
    // Called from the fatal-signal handler: no locks, no heap. A torn name
    // (writer mid-snprintf) at worst makes shm_unlink fail with ENOENT.
    for (auto& slot : g_segments) {
        if (slot[0] != '\0') shm_unlink(slot);
    }
}

void shm_sweep_stale() {
    // Unlink segments left by SIGKILLed servers. Liveness is decided by
    // flock, not pid probing: every live pool holds LOCK_EX on its segment
    // fd, and locks die with the owner — correct even when servers live in
    // different pid namespaces sharing one /dev/shm mount.
    DIR* d = opendir("/dev/shm");
    if (d == nullptr) return;
    while (dirent* e = readdir(d)) {
        if (strncmp(e->d_name, "its.", 4) != 0) continue;
        std::string name = std::string("/") + e->d_name;
        int fd = shm_open(name.c_str(), O_RDWR, 0);
        if (fd < 0) continue;
        if (flock(fd, LOCK_EX | LOCK_NB) == 0 && shm_unlink(name.c_str()) == 0)
            ITS_LOG_INFO("swept stale shm segment %s (owner is gone)", name.c_str());
        close(fd);  // releases our probe lock
    }
    closedir(d);
}

MemoryPool::MemoryPool(size_t pool_size, size_t block_size, bool pin,
                       const std::string& shm_name)
    : pool_size_(pool_size), block_size_(block_size) {
    if (!is_pow2(block_size)) throw std::invalid_argument("block_size must be a power of two");
    if (pool_size == 0 || pool_size % block_size != 0)
        throw std::invalid_argument("pool_size must be a positive multiple of block_size");
    alloc_.init(pool_size / block_size);

    if (!shm_name.empty()) {
        int err = 0;
        int fd = shm_open(shm_name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
        if (fd < 0) err = errno;
        // Take the liveness lock before fallocate: shm_sweep_stale treats an
        // unlocked segment as abandoned, and a multi-GB fallocate is a long
        // window for a concurrently starting server to sweep us mid-setup.
        if (fd >= 0) flock(fd, LOCK_EX | LOCK_NB);
        // posix_fallocate (not just ftruncate): reserve the tmpfs pages now so
        // an over-committed /dev/shm fails cleanly here — triggering the
        // anonymous fallback — instead of SIGBUSing the first touch mid-put.
        // (It returns its error code without setting errno.)
        if (fd >= 0) {
            if (ftruncate(fd, static_cast<off_t>(pool_size)) != 0) err = errno;
            if (err == 0) err = posix_fallocate(fd, 0, static_cast<off_t>(pool_size));
            if (err != 0) {
                close(fd);
                shm_unlink(shm_name.c_str());
                fd = -1;
            }
        }
        if (fd >= 0) {
            void* mem =
                mmap(nullptr, pool_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
            if (mem != MAP_FAILED) {
                base_ = static_cast<char*>(mem);
                shm_backed_ = true;
                shm_name_ = shm_name;
                shm_fd_ = fd;  // keeps the flock liveness marker until death
                shm_registry_add(shm_name.c_str());
            } else {
                err = errno;
                close(fd);
                shm_unlink(shm_name.c_str());
            }
        }
        if (!shm_backed_)
            ITS_LOG_WARN("shm pool %s unavailable (%s); falling back to anonymous memory",
                         shm_name.c_str(), strerror(err));
    }
    if (base_ == nullptr) {
        void* mem = nullptr;
        if (posix_memalign(&mem, kAlignment, pool_size) != 0)
            throw std::bad_alloc();
        base_ = static_cast<char*>(mem);
    }

    if (pin) {
        // Pin so DCN send/recv never faults mid-transfer. Containers commonly
        // cap RLIMIT_MEMLOCK, so a failure downgrades to unpinned, not fatal.
        if (mlock(base_, pool_size_) == 0) {
            pinned_ = true;
        } else {
            ITS_LOG_WARN("mlock(%zu bytes) failed; pool is unpinned", pool_size_);
        }
    }
    ITS_LOG_INFO("mempool: %zu MB, block %zu KB, %zu blocks, pinned=%d",
                 pool_size_ >> 20, block_size_ >> 10, alloc_.total, (int)pinned_);
}

MemoryPool::~MemoryPool() {
    if (base_ != nullptr) {
        if (pinned_) munlock(base_, pool_size_);
        if (shm_backed_) {
            munmap(base_, pool_size_);
            shm_unlink(shm_name_.c_str());
            shm_registry_remove(shm_name_.c_str());
            close(shm_fd_);  // releases the liveness flock
        } else {
            free(base_);
        }
    }
}

// The first-fit run scan itself lives in bitmap_alloc.h, shared with the
// spill tier (one allocator, two backing stores).
void* MemoryPool::allocate(size_t size) {
    if (size == 0) return nullptr;
    size_t nblocks = (size + block_size_ - 1) / block_size_;
    size_t start = alloc_.alloc_run(nblocks);
    if (start == SIZE_MAX) return nullptr;
    return base_ + start * block_size_;
}

bool MemoryPool::deallocate(void* ptr, size_t size) {
    char* p = static_cast<char*>(ptr);
    if (!contains(p) || (p - base_) % block_size_ != 0) {
        ITS_LOG_ERROR("deallocate of foreign/misaligned pointer %p", ptr);
        return false;
    }
    size_t first = static_cast<size_t>(p - base_) / block_size_;
    size_t nblocks = (size + block_size_ - 1) / block_size_;
    if (first + nblocks > alloc_.total) {
        ITS_LOG_ERROR("deallocate past pool end (%zu blocks at %zu)", nblocks, first);
        return false;
    }
    // Double-free detection (reference /root/reference/src/mempool.cpp:114-156).
    for (size_t i = first; i < first + nblocks; i++) {
        if (!alloc_.is_used(i)) {
            ITS_LOG_ERROR("double free detected at block %zu", i);
            return false;
        }
    }
    alloc_.free_run(first, nblocks);
    return true;
}

MM::MM(size_t initial_pool_size, size_t block_size, bool pin, bool use_shm)
    : block_size_(block_size), pin_(pin) {
    if (use_shm) {
        shm_sweep_stale();
        // Unique prefix per MM instance; pools are "<prefix>.<index>".
        std::random_device rd;
        char buf[64];
        snprintf(buf, sizeof(buf), "/its.%d.%08x", static_cast<int>(getpid()), rd());
        shm_prefix_ = std::make_unique<std::string>(buf);
    }
    pools_.push_back(
        std::make_unique<MemoryPool>(initial_pool_size, block_size, pin, next_shm_name()));
    if (use_shm && pools_[0]->shm_name().empty()) shm_prefix_.reset();  // fell back
}

std::string MM::next_shm_name() {
    if (shm_prefix_ == nullptr) return "";
    return *shm_prefix_ + "." + std::to_string(pools_.size());
}

std::vector<PoolDirEntry> MM::pool_dir() const {
    std::vector<PoolDirEntry> dir;
    if (shm_prefix_ == nullptr) return dir;
    for (size_t i = 0; i < pools_.size(); i++) {
        if (pools_[i]->shm_name().empty()) continue;
        dir.push_back(PoolDirEntry{static_cast<uint16_t>(i), pools_[i]->shm_name(),
                                   static_cast<uint64_t>(pools_[i]->size())});
    }
    return dir;
}

PoolLoc MM::locate(const void* ptr) const {
    for (size_t i = 0; i < pools_.size(); i++) {
        if (pools_[i]->contains(ptr)) {
            return PoolLoc{static_cast<uint16_t>(i),
                           static_cast<uint64_t>(static_cast<const char*>(ptr) -
                                                 static_cast<const char*>(pools_[i]->base())),
                           true};
        }
    }
    return PoolLoc{};
}

bool MM::allocate(size_t size, size_t n, const std::function<void(void*, size_t)>& cb,
                  std::vector<Lease>* out) {
    std::vector<Lease> leases;
    leases.reserve(n);
    for (size_t i = 0; i < n; i++) {
        void* ptr = nullptr;
        MemoryPool* owner = nullptr;
        for (auto& pool : pools_) {
            ptr = pool->allocate(size);
            if (ptr != nullptr) {
                owner = pool.get();
                break;
            }
        }
        if (ptr == nullptr) {
            // All-or-nothing, as in the reference: roll back this batch.
            for (const auto& l : leases) l.pool->deallocate(l.ptr, l.size);
            return false;
        }
        leases.push_back(Lease{ptr, size, owner});
        if (cb) cb(ptr, i);
    }
    if (out != nullptr) {
        out->insert(out->end(), leases.begin(), leases.end());
    }
    return true;
}

void MM::deallocate(const Lease& lease) { lease.pool->deallocate(lease.ptr, lease.size); }

void MM::deallocate(void* ptr, size_t size) {
    for (auto& pool : pools_) {
        if (pool->contains(ptr)) {
            pool->deallocate(ptr, size);
            return;
        }
    }
    ITS_LOG_ERROR("deallocate: pointer %p not owned by any pool", ptr);
}

bool MM::extend(size_t pool_size) {
    try {
        pools_.push_back(
            std::make_unique<MemoryPool>(pool_size, block_size_, pin_, next_shm_name()));
        ITS_LOG_INFO("mempool extended: now %zu pools, %zu MB total", pools_.size(),
                     total_bytes() >> 20);
        return true;
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("mempool extend failed: %s", e.what());
        return false;
    }
}

double MM::usage() const {
    size_t used = 0, total = 0;
    for (const auto& pool : pools_) {
        used += pool->used_blocks();
        total += pool->total_blocks();
    }
    return total == 0 ? 0.0 : static_cast<double>(used) / static_cast<double>(total);
}

size_t MM::total_bytes() const {
    size_t total = 0;
    for (const auto& pool : pools_) total += pool->total_blocks() * pool->block_size();
    return total;
}

size_t MM::used_bytes() const {
    size_t used = 0;
    for (const auto& pool : pools_) used += pool->used_blocks() * pool->block_size();
    return used;
}

bool MM::pinned() const {
    for (const auto& pool : pools_) {
        if (!pool->pinned()) return false;
    }
    return !pools_.empty();
}

}  // namespace its
