#include "its/mempool.h"

#include <strings.h>
#include <sys/mman.h>

#include <cstdlib>
#include <cstring>
#include <stdexcept>

#include "its/log.h"

namespace its {

namespace {
constexpr size_t kAlignment = 4096;

bool is_pow2(size_t v) { return v != 0 && (v & (v - 1)) == 0; }
}  // namespace

MemoryPool::MemoryPool(size_t pool_size, size_t block_size, bool pin)
    : pool_size_(pool_size), block_size_(block_size) {
    if (!is_pow2(block_size)) throw std::invalid_argument("block_size must be a power of two");
    if (pool_size == 0 || pool_size % block_size != 0)
        throw std::invalid_argument("pool_size must be a positive multiple of block_size");
    total_blocks_ = pool_size / block_size;

    void* mem = nullptr;
    if (posix_memalign(&mem, kAlignment, pool_size) != 0)
        throw std::bad_alloc();
    base_ = static_cast<char*>(mem);

    if (pin) {
        // Pin so DCN send/recv never faults mid-transfer. Containers commonly
        // cap RLIMIT_MEMLOCK, so a failure downgrades to unpinned, not fatal.
        if (mlock(base_, pool_size_) == 0) {
            pinned_ = true;
        } else {
            ITS_LOG_WARN("mlock(%zu bytes) failed; pool is unpinned", pool_size_);
        }
    }
    bitmap_.assign((total_blocks_ + 63) / 64, 0);
    ITS_LOG_INFO("mempool: %zu MB, block %zu KB, %zu blocks, pinned=%d",
                 pool_size_ >> 20, block_size_ >> 10, total_blocks_, (int)pinned_);
}

MemoryPool::~MemoryPool() {
    if (base_ != nullptr) {
        if (pinned_) munlock(base_, pool_size_);
        free(base_);
    }
}

size_t MemoryPool::find_free_run(size_t nblocks) {
    // First-fit scan. Fast path: skip fully-used words, find the first zero
    // bit with ffsll (reference uses ctz the same way,
    // /root/reference/src/mempool.cpp:55-112), then verify run length.
    size_t idx = 0;
    while (idx < total_blocks_) {
        size_t word = idx / 64;
        if (bitmap_[word] == ~0ull) {
            idx = (word + 1) * 64;
            continue;
        }
        uint64_t inv = ~bitmap_[word] & (~0ull << (idx % 64));
        if (inv == 0) {
            idx = (word + 1) * 64;
            continue;
        }
        size_t start = word * 64 + static_cast<size_t>(__builtin_ctzll(inv));
        if (start >= total_blocks_) break;
        // Check the run [start, start+nblocks).
        size_t run = 0;
        while (run < nblocks && start + run < total_blocks_) {
            size_t b = start + run;
            if (bitmap_[b / 64] & (1ull << (b % 64))) break;
            run++;
        }
        if (run == nblocks) return start;
        idx = start + run + 1;
    }
    return SIZE_MAX;
}

void MemoryPool::mark(size_t first_block, size_t nblocks, bool used) {
    for (size_t i = first_block; i < first_block + nblocks; i++) {
        uint64_t bit = 1ull << (i % 64);
        if (used) {
            bitmap_[i / 64] |= bit;
        } else {
            bitmap_[i / 64] &= ~bit;
        }
    }
}

void* MemoryPool::allocate(size_t size) {
    if (size == 0) return nullptr;
    size_t nblocks = (size + block_size_ - 1) / block_size_;
    size_t start = find_free_run(nblocks);
    if (start == SIZE_MAX) return nullptr;
    mark(start, nblocks, /*used=*/true);
    used_blocks_ += nblocks;
    return base_ + start * block_size_;
}

bool MemoryPool::deallocate(void* ptr, size_t size) {
    char* p = static_cast<char*>(ptr);
    if (!contains(p) || (p - base_) % block_size_ != 0) {
        ITS_LOG_ERROR("deallocate of foreign/misaligned pointer %p", ptr);
        return false;
    }
    size_t first = static_cast<size_t>(p - base_) / block_size_;
    size_t nblocks = (size + block_size_ - 1) / block_size_;
    if (first + nblocks > total_blocks_) {
        ITS_LOG_ERROR("deallocate past pool end (%zu blocks at %zu)", nblocks, first);
        return false;
    }
    // Double-free detection (reference /root/reference/src/mempool.cpp:114-156).
    for (size_t i = first; i < first + nblocks; i++) {
        if (!(bitmap_[i / 64] & (1ull << (i % 64)))) {
            ITS_LOG_ERROR("double free detected at block %zu", i);
            return false;
        }
    }
    mark(first, nblocks, /*used=*/false);
    used_blocks_ -= nblocks;
    return true;
}

MM::MM(size_t initial_pool_size, size_t block_size, bool pin)
    : block_size_(block_size), pin_(pin) {
    pools_.push_back(std::make_unique<MemoryPool>(initial_pool_size, block_size, pin));
}

bool MM::allocate(size_t size, size_t n, const std::function<void(void*, size_t)>& cb,
                  std::vector<Lease>* out) {
    std::vector<Lease> leases;
    leases.reserve(n);
    for (size_t i = 0; i < n; i++) {
        void* ptr = nullptr;
        MemoryPool* owner = nullptr;
        for (auto& pool : pools_) {
            ptr = pool->allocate(size);
            if (ptr != nullptr) {
                owner = pool.get();
                break;
            }
        }
        if (ptr == nullptr) {
            // All-or-nothing, as in the reference: roll back this batch.
            for (const auto& l : leases) l.pool->deallocate(l.ptr, l.size);
            return false;
        }
        leases.push_back(Lease{ptr, size, owner});
        if (cb) cb(ptr, i);
    }
    if (out != nullptr) {
        out->insert(out->end(), leases.begin(), leases.end());
    }
    return true;
}

void MM::deallocate(const Lease& lease) { lease.pool->deallocate(lease.ptr, lease.size); }

void MM::deallocate(void* ptr, size_t size) {
    for (auto& pool : pools_) {
        if (pool->contains(ptr)) {
            pool->deallocate(ptr, size);
            return;
        }
    }
    ITS_LOG_ERROR("deallocate: pointer %p not owned by any pool", ptr);
}

bool MM::extend(size_t pool_size) {
    try {
        pools_.push_back(std::make_unique<MemoryPool>(pool_size, block_size_, pin_));
        ITS_LOG_INFO("mempool extended: now %zu pools, %zu MB total", pools_.size(),
                     total_bytes() >> 20);
        return true;
    } catch (const std::exception& e) {
        ITS_LOG_ERROR("mempool extend failed: %s", e.what());
        return false;
    }
}

double MM::usage() const {
    size_t used = 0, total = 0;
    for (const auto& pool : pools_) {
        used += pool->used_blocks();
        total += pool->total_blocks();
    }
    return total == 0 ? 0.0 : static_cast<double>(used) / static_cast<double>(total);
}

size_t MM::total_bytes() const {
    size_t total = 0;
    for (const auto& pool : pools_) total += pool->total_blocks() * pool->block_size();
    return total;
}

size_t MM::used_bytes() const {
    size_t used = 0;
    for (const auto& pool : pools_) used += pool->used_blocks() * pool->block_size();
    return used;
}

bool MM::pinned() const {
    for (const auto& pool : pools_) {
        if (!pool->pinned()) return false;
    }
    return !pools_.empty();
}

}  // namespace its
