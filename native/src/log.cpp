#include "its/log.h"

#include <atomic>
#include <cstdio>
#include <ctime>
#include <mutex>

namespace its {

namespace {
std::atomic<int> g_level{static_cast<int>(LogLevel::kInfo)};
std::atomic<LogSink> g_sink{nullptr};
std::mutex g_stderr_mu;

const char* level_name(int level) {
    switch (level) {
        case 0:
            return "DEBUG";
        case 1:
            return "INFO";
        case 2:
            return "WARN";
        case 3:
            return "ERROR";
        default:
            return "?";
    }
}
}  // namespace

void set_log_level(LogLevel level) { g_level.store(static_cast<int>(level)); }

LogLevel log_level() { return static_cast<LogLevel>(g_level.load()); }

void set_log_sink(LogSink sink) { g_sink.store(sink); }

void log_msg(LogLevel level, const char* fmt, ...) {
    int lvl = static_cast<int>(level);
    if (lvl < g_level.load(std::memory_order_relaxed)) return;

    char buf[2048];
    va_list args;
    va_start(args, fmt);
    vsnprintf(buf, sizeof(buf), fmt, args);
    va_end(args);

    LogSink sink = g_sink.load();
    if (sink != nullptr) {
        sink(lvl, buf);
        return;
    }
    std::lock_guard<std::mutex> lock(g_stderr_mu);
    char ts[32];
    std::time_t now = std::time(nullptr);
    std::tm tm_buf;
    localtime_r(&now, &tm_buf);
    std::strftime(ts, sizeof(ts), "%H:%M:%S", &tm_buf);
    fprintf(stderr, "[%s] [infinistore-tpu] [%s] %s\n", ts, level_name(lvl), buf);
}

}  // namespace its
