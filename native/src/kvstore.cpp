#include "its/kvstore.h"

#include <cstring>

#include "its/log.h"

namespace its {

void KVStore::release_entry(Entry& e) {
    if (e.spilled()) spill_->free_slot(e.spill_off, e.spill_size);
    e.spill_off = -1;
}

void KVStore::commit(const std::string& key, BlockRef block) {
    auto it = map_.find(key);
    if (it != map_.end()) {
        // Overwrite: replace in place and touch. The old RAM block is freed
        // once in-flight readers release it; an old spill slot is freed now.
        Entry& e = it->second;
        // splice, not erase+push_front: moves the existing node (no node
        // free/alloc, no key copy) and keeps e.lru_it valid. Also hoists a
        // spilled entry's node from spill_lru_ into lru_.
        lru_.splice(lru_.begin(), e.spilled() ? spill_lru_ : lru_, e.lru_it);
        release_entry(e);
        e.block = std::move(block);
        return;
    }
    lru_.push_front(key);
    map_.emplace(key, Entry{std::move(block), -1, 0, lru_.begin()});
}

// Demote the entry's bytes into the spill file; true on success. Frees the
// RAM block (modulo in-flight readers holding the BlockRef).
bool KVStore::demote(const std::string& key, Entry& e) {
    size_t size = e.block->size();
    // An entry larger than the whole spill file can never fit — bail BEFORE
    // the drop loop, or one oversized cold value would drain every spilled
    // entry (mass data loss) and still fail.
    if (size > spill_->total_bytes()) return false;
    int64_t off = spill_->alloc(size);
    while (off < 0 && drop_oldest_spilled()) off = spill_->alloc(size);
    if (off < 0) return false;
    memcpy(spill_->data(off), e.block->data(), size);
    e.block.reset();
    e.spill_off = off;
    e.spill_size = size;
    spill_lru_.push_front(key);
    e.lru_it = spill_lru_.begin();
    return true;
}

// Drop the coldest spilled entry for real. Returns false when none exist.
bool KVStore::drop_oldest_spilled() {
    if (spill_lru_.empty()) return false;
    const std::string victim = spill_lru_.back();
    auto it = map_.find(victim);
    if (it != map_.end()) {
        release_entry(it->second);
        map_.erase(it);
    }
    spill_lru_.pop_back();
    spill_drops_++;
    return true;
}

// Bring a spilled entry back into a RAM pool. Owns the entry's full
// lifecycle: on success it is re-linked into the RAM LRU; on failure (RAM
// unobtainable even after demoting colder entries) it stays SPILLED and
// nullptr is returned — the caller surfaces resource pressure, the bytes
// survive for a smaller or later read.
BlockRef KVStore::promote(const std::string& key,
                          std::unordered_map<std::string, Entry>::iterator it) {
    Entry& e = it->second;
    // Detach from the spill LRU FIRST: the eviction below may demote other
    // entries and, if the file fills, drop the oldest spilled — which must
    // never be able to select (and erase) the entry we are promoting.
    spill_lru_.erase(e.lru_it);
    size_t size = e.spill_size;
    std::vector<Lease> leases;
    bool got;
    if (promote_alloc_) {
        // The server's configured allocation policy (evict ratios +
        // auto_increase extension) — promotion behaves like any other
        // allocation.
        got = promote_alloc_(size, &leases);
    } else {
        auto no_op = [](void*, size_t) {};
        got = mm_->allocate(size, 1, no_op, &leases);
        if (!got) {
            evict(0.8, 0.0);  // conservative fallback: demote colder entries
            got = mm_->allocate(size, 1, no_op, &leases);
        }
    }
    if (!got) {
        // RAM unobtainable (e.g. a huge batch pinning every promoted block):
        // KEEP the entry spilled — its bytes are intact and a smaller or
        // later read can still serve it. Re-link as most-recent so the
        // failed read does not also make it first in line to be dropped.
        ITS_LOG_WARN("spill: cannot promote %zu bytes (RAM exhausted)", size);
        spill_lru_.push_front(key);
        e.lru_it = spill_lru_.begin();
        return nullptr;
    }
    auto block = std::make_shared<Block>(mm_, leases[0].ptr, size);
    memcpy(block->data(), spill_->data(e.spill_off), size);
    release_entry(e);
    e.block = block;
    lru_.push_front(key);
    e.lru_it = lru_.begin();
    promotions_++;
    return block;
}

BlockRef KVStore::get(const std::string& key) {
    auto it = map_.find(key);
    if (it == map_.end()) return nullptr;
    Entry& e = it->second;
    if (e.spilled()) return promote(key, it);
    lru_.splice(lru_.begin(), lru_, e.lru_it);  // O(1) touch, no node churn
    return e.block;
}

bool KVStore::exists(const std::string& key) const { return map_.count(key) != 0; }

BlockRef KVStore::overwrite_slot(const std::string& key, size_t size) {
    auto it = map_.find(key);
    if (it == map_.end()) return nullptr;
    Entry& e = it->second;
    if (e.block == nullptr || e.block->size() != size) return nullptr;
    // use_count()==1 means the map holds the only reference: no suspended
    // GET continuation is mid-stream on this block, so mutating it in
    // place cannot tear a reader's snapshot.
    if (e.block.use_count() != 1) return nullptr;
    lru_.splice(lru_.begin(), lru_, e.lru_it);  // O(1) touch, no node churn
    return e.block;
}

bool KVStore::overwrite_eligible(const std::string& key, size_t size) const {
    auto it = map_.find(key);
    if (it == map_.end()) return false;
    const Entry& e = it->second;
    return e.block != nullptr && e.block->size() == size &&
           e.block.use_count() == 1;
}

size_t KVStore::remove(const std::vector<std::string>& keys) {
    size_t removed = 0;
    for (const auto& key : keys) {
        auto it = map_.find(key);
        if (it == map_.end()) continue;
        Entry& e = it->second;
        (e.spilled() ? spill_lru_ : lru_).erase(e.lru_it);
        release_entry(e);
        map_.erase(it);
        removed++;
    }
    return removed;
}

size_t KVStore::purge() {
    size_t n = map_.size();
    for (auto& [key, e] : map_) release_entry(e);
    map_.clear();
    lru_.clear();
    spill_lru_.clear();
    return n;
}

int32_t KVStore::match_last_index(const std::vector<std::string>& keys) const {
    // Binary search is only correct under the prefix property; this matches
    // the reference's behavior exactly, including on inputs that violate it
    // (test_infinistore.py:291-311 relies on that). Spilled entries count as
    // present — no promotion on a control op.
    size_t lo = 0, hi = keys.size();
    while (lo < hi) {
        size_t mid = lo + (hi - lo) / 2;
        if (exists(keys[mid])) {
            lo = mid + 1;
        } else {
            hi = mid;
        }
    }
    return static_cast<int32_t>(lo) - 1;
}

bool KVStore::evict_one() {
    if (lru_.empty()) return false;
    const std::string victim = lru_.back();
    lru_.pop_back();
    auto it = map_.find(victim);
    if (it == map_.end()) return true;  // lockstep violation; tolerate
    if (spill_ != nullptr && demote(victim, it->second)) return true;
    release_entry(it->second);
    map_.erase(it);
    return true;
}

size_t KVStore::evict(double min_ratio, double max_ratio) {
    if (mm_->usage() < max_ratio) return 0;
    size_t evicted = 0;
    while (mm_->usage() > min_ratio && !lru_.empty()) {
        const std::string victim = lru_.back();
        lru_.pop_back();
        auto it = map_.find(victim);
        // The LRU and map are kept in lockstep; a miss here is a logic bug.
        if (it == map_.end()) continue;
        if (spill_ != nullptr && demote(victim, it->second)) {
            evicted++;
            continue;
        }
        release_entry(it->second);
        map_.erase(it);
        evicted++;
    }
    if (evicted > 0) {
        ITS_LOG_INFO("evicted %zu entries (%zu now spilled), usage %.2f", evicted,
                     spill_lru_.size(), mm_->usage());
    }
    return evicted;
}

}  // namespace its
