#include "its/kvstore.h"

#include "its/log.h"

namespace its {

void KVStore::commit(const std::string& key, BlockRef block) {
    auto it = map_.find(key);
    if (it != map_.end()) {
        // Overwrite: replace the block in place and touch. The old block is
        // freed once in-flight readers release it.
        lru_.erase(it->second.lru_it);
        lru_.push_front(key);
        it->second.block = std::move(block);
        it->second.lru_it = lru_.begin();
        return;
    }
    lru_.push_front(key);
    map_.emplace(key, Entry{std::move(block), lru_.begin()});
}

BlockRef KVStore::get(const std::string& key) {
    auto it = map_.find(key);
    if (it == map_.end()) return nullptr;
    lru_.erase(it->second.lru_it);
    lru_.push_front(key);
    it->second.lru_it = lru_.begin();
    return it->second.block;
}

BlockRef KVStore::peek(const std::string& key) const {
    auto it = map_.find(key);
    return it == map_.end() ? nullptr : it->second.block;
}

bool KVStore::exists(const std::string& key) const { return map_.count(key) != 0; }

size_t KVStore::remove(const std::vector<std::string>& keys) {
    size_t removed = 0;
    for (const auto& key : keys) {
        auto it = map_.find(key);
        if (it == map_.end()) continue;
        lru_.erase(it->second.lru_it);
        map_.erase(it);
        removed++;
    }
    return removed;
}

size_t KVStore::purge() {
    size_t n = map_.size();
    map_.clear();
    lru_.clear();
    return n;
}

int32_t KVStore::match_last_index(const std::vector<std::string>& keys) const {
    // Binary search is only correct under the prefix property; this matches
    // the reference's behavior exactly, including on inputs that violate it
    // (test_infinistore.py:291-311 relies on that).
    size_t lo = 0, hi = keys.size();
    while (lo < hi) {
        size_t mid = lo + (hi - lo) / 2;
        if (exists(keys[mid])) {
            lo = mid + 1;
        } else {
            hi = mid;
        }
    }
    return static_cast<int32_t>(lo) - 1;
}

size_t KVStore::evict(double min_ratio, double max_ratio) {
    if (mm_->usage() < max_ratio) return 0;
    size_t evicted = 0;
    while (mm_->usage() > min_ratio && !lru_.empty()) {
        const std::string& victim = lru_.back();
        auto it = map_.find(victim);
        // The LRU and map are kept in lockstep; a miss here is a logic bug.
        if (it != map_.end()) map_.erase(it);
        lru_.pop_back();
        evicted++;
    }
    if (evicted > 0) ITS_LOG_INFO("evicted %zu entries, usage now %.2f", evicted, mm_->usage());
    return evicted;
}

}  // namespace its
