#include "its/server.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <future>

#include "its/iovec_util.h"
#include "its/net_util.h"
#include "its/log.h"
#include "its/ring.h"
#include "its/streamcopy.h"

namespace its {

namespace {

uint64_t now_us() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000ull + ts.tv_nsec / 1000;
}

// HDR-style sub-bucketed index: values < 2^kSubBits map exactly; above, the
// kSubBits bits below the MSB pick a sub-bucket within the octave.
int lat_bucket(uint64_t us) {
    constexpr int sub = OpStats::kSubBits;
    if (us < (1ull << sub)) return static_cast<int>(us);
    int msb = 63 - __builtin_clzll(us);
    int shift = msb - sub;
    int idx = (1 << sub) + (shift << sub) +
              static_cast<int>((us >> shift) & ((1 << sub) - 1));
    return idx < OpStats::kBuckets ? idx : OpStats::kBuckets - 1;
}

// Inverse bucket geometry (single source for every decoder of lat_bucket's
// index space): bucket ``idx`` covers [base, base + step).
void lat_bucket_range(int idx, uint64_t* base, uint64_t* step) {
    constexpr int sub = OpStats::kSubBits;
    if (idx < (1 << sub)) {
        *base = static_cast<uint64_t>(idx);
        *step = 1;
        return;
    }
    int group = (idx - (1 << sub)) >> sub;
    int s = (idx - (1 << sub)) & ((1 << sub) - 1);
    *base = (static_cast<uint64_t>((1 << sub) + s)) << group;
    *step = 1ull << group;
}

// Geometric midpoint of a bucket (inverse of lat_bucket).
double lat_bucket_mid(int idx) {
    uint64_t base, step;
    lat_bucket_range(idx, &base, &step);
    if (step == 1) return static_cast<double>(base);
    return static_cast<double>(base) + static_cast<double>(step) / 2.0;
}

}  // namespace

void OpStats::record(uint64_t us, uint64_t in_bytes, uint64_t out_bytes, bool ok) {
    count++;
    if (!ok) errors++;
    bytes_in += in_bytes;
    bytes_out += out_bytes;
    total_us += us;
    lat_buckets[lat_bucket(us)]++;
}

uint64_t OpStats::bucket_le_us(int idx) {
    // Inclusive integer upper bound of lat_bucket's bucket ``idx`` (the
    // Prometheus `le` the /metrics histogram export uses).
    uint64_t base, step;
    lat_bucket_range(idx, &base, &step);
    return base + step - 1;
}

double OpStats::percentile_us(double q) const {
    if (count == 0) return 0.0;
    uint64_t seen = 0;
    // Smallest value whose cumulative share reaches q (ceil, not truncate:
    // p50 of 81 samples is rank 41).
    uint64_t rank =
        static_cast<uint64_t>(std::ceil(q * static_cast<double>(count)));
    if (rank == 0) rank = 1;
    for (int i = 0; i < kBuckets; i++) {
        seen += lat_buckets[i];
        if (seen >= rank) return lat_bucket_mid(i);
    }
    return 0.0;
}

// Per-connection state machine (reference Client,
// /root/reference/src/infinistore.cpp:55-109; read states :43-47).
struct Server::Conn {
    enum class RState { kHeader, kBody, kPayload, kDrain, kSuspended };

    int fd = -1;
    bool dead = false;
    RState rstate = RState::kHeader;
    ReqHeader hdr{};
    size_t hdr_got = 0;
    std::vector<uint8_t> body;
    size_t body_got = 0;

    // Payload scatter targets for put paths: socket bytes land directly in
    // pool blocks (the zero-copy half of the old server-side RDMA READ).
    std::vector<iovec> rx_iov;
    ScatterCursor rx_cur;
    std::vector<std::string> pending_keys;
    std::vector<BlockRef> pending_blocks;
    uint64_t drain_remaining = 0;
    uint32_t drain_status = kStatusOk;

    uint8_t cur_op = 0;
    uint64_t op_start_us = 0;

    // Per-op trace stamps (docs/observability.md): set by trace_begin when
    // the metadata carried a wire trace context, published to the server's
    // tick ring by trace_finish. Zero trace_id = untraced (every stamp
    // site is a single-branch no-op).
    uint64_t trace_id = 0;
    uint64_t trace_parent = 0;
    uint64_t trace_prio = 0;
    uint64_t trace_first_us = 0;
    uint64_t trace_last_us = 0;

    struct OutMsg {
        RespHeader hdr;
        std::vector<uint8_t> body;
        std::vector<iovec> payload;
        std::vector<BlockRef> refs;  // keeps blocks alive while streaming
        size_t sent = 0;
        size_t total = 0;
    };
    std::deque<OutMsg> outq;
    bool epollout_armed = false;
    bool epollin_armed = true;

    // Budget-sliced one-RTT segment op (kOpPutFrom / kOpGetInto): the
    // reactor runs at most ServerConfig::slice_bytes of pool/spill memcpy
    // work per loop tick, so a spill-heavy batch cannot stall every other
    // connection for milliseconds (r3 VERDICT weak #5). While suspended the
    // conn's EPOLLIN is disarmed — still one op at a time per connection.
    // Two forms: PutFrom/GetInto carry full phase state; PutAlloc/GetLoc
    // (two-phase shm control ops, no server-side payload copies) suspend
    // with op only and re-dispatch from the still-buffered body next tick —
    // their only unbounded work is the reclaim/promote loop, whose partial
    // progress (demotions, promotions) persists across retries.
    struct SegCont {
        uint8_t op = 0;
        // QoS class of the op this continuation slices (protocol.h Priority):
        // decides which cont queue the conn waits in between slices.
        uint8_t prio = kPriorityForeground;
        SegBatchMeta m;
        enum class Phase { kAlloc, kPin, kCopy } phase = Phase::kAlloc;
        size_t idx = 0;     // blocks allocated (PutFrom) / pinned (GetInto)
        size_t copied = 0;  // blocks memcpy'd
        std::vector<BlockRef> blocks;
        // Descriptor-ring source (docs/descriptor_ring.md): completion goes
        // to the ring (ring_finish) instead of a socket response.
        bool from_ring = false;
        uint64_t ring_token = 0;
    };
    std::unique_ptr<SegCont> cont;
    bool queued_cont = false;

    // Attached descriptor ring (kOpRingAttach). SQ consumption and CQ
    // publication are reactor-thread-only; the client process is the other
    // side of the shared cursors (ring.h discipline). Decoded descriptors
    // wait in the per-class pending queues until the conn's single cont
    // slot frees up — foreground first.
    struct RingSrv {
        RingView view;
        uint64_t sq_seq = 0;  // descriptors consumed
        uint64_t cq_seq = 0;  // completions published
        struct PendingDesc {
            uint8_t op = 0;
            uint64_t token = 0;
            SegBatchMeta m;
        };
        std::deque<PendingDesc> pending_fg, pending_bg;
    };
    std::unique_ptr<RingSrv> ring;

    // Shm fast-path tickets. A put ticket holds allocated-but-unpublished
    // blocks between PutAlloc and PutCommit; a get ticket pins committed
    // blocks while the client copies them out of the mapped pools. Both die
    // with the connection (blocks freed / refs dropped via BlockRef).
    struct PendingPut {
        std::vector<std::string> keys;
        std::vector<BlockRef> blocks;
        // Stamped at the PutAlloc leg so the commit-time stats record spans
        // the whole logical op (alloc RTT + client memcpy + commit RTT), not
        // just the commit leg.
        uint64_t start_us = 0;
    };
    uint64_t next_ticket = 1;
    std::unordered_map<uint64_t, PendingPut> pending_puts;
    std::unordered_map<uint64_t, std::vector<BlockRef>> pending_gets;

    // Client shm segments mapped for the one-RTT pull/push path.
    struct SegMap {
        char* base = nullptr;
        size_t size = 0;
    };
    std::unordered_map<uint16_t, SegMap> segments;

    ~Conn() {
        for (auto& [id, seg] : segments)
            if (seg.base != nullptr) munmap(seg.base, seg.size);
        if (ring != nullptr && ring->view.base != nullptr)
            munmap(ring->view.base, ring->view.size);
    }

    void reset_read() {
        rstate = RState::kHeader;
        hdr_got = 0;
        body.clear();
        body_got = 0;
        rx_iov.clear();
        rx_cur.reset();
        pending_keys.clear();
        pending_blocks.clear();
        drain_remaining = 0;
    }
};

Server::Server(const ServerConfig& config) : config_(config) {
    mm_ = std::make_unique<MM>(config.prealloc_bytes, config.block_size, config.pin_memory,
                               config.enable_shm);
    if (!config.spill_dir.empty() && config.spill_bytes > 0) {
        spill_ = std::make_unique<SpillFile>(config.spill_dir, config.spill_bytes,
                                             config.block_size);
        if (!spill_->ok()) spill_.reset();  // tier disabled; already logged
    }
    kv_ = std::make_unique<KVStore>(mm_.get(), spill_.get());
    // Promotion allocates through the server's configured policy (evict
    // ratios + auto_increase extension) — same treatment as PUT allocations.
    kv_->set_promote_alloc([this](size_t size, std::vector<Lease>* leases) {
        return alloc_blocks(size, 1, leases);
    });
}

Server::~Server() { stop(); }

bool Server::start() {
    install_crash_handler();  // reference installs on register_server (:994-998)
    listen_fd_ = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(config_.service_port));
    if (inet_pton(AF_INET, config_.bind_addr.c_str(), &addr.sin_addr) != 1) {
        ITS_LOG_ERROR("bad bind address %s", config_.bind_addr.c_str());
        close(listen_fd_);
        listen_fd_ = -1;
        return false;
    }
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        listen(listen_fd_, 128) != 0) {
        ITS_LOG_ERROR("bind/listen on %s:%d failed: %s", config_.bind_addr.c_str(),
                      config_.service_port, strerror(errno));
        close(listen_fd_);
        listen_fd_ = -1;
        return false;
    }
    socklen_t len = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    bound_port_ = ntohs(addr.sin_port);

    epoll_fd_ = epoll_create1(EPOLL_CLOEXEC);
    wake_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = listen_fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, listen_fd_, &ev);
    ev.data.fd = wake_fd_;
    epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, wake_fd_, &ev);

    running_.store(true);
    stop_requested_.store(false);
    thread_ = std::thread([this] { loop(); });
    ITS_LOG_INFO("server listening on %s:%d (pool %zu MB, block %zu KB)",
                 config_.bind_addr.c_str(), bound_port_, config_.prealloc_bytes >> 20,
                 config_.block_size >> 10);
    return true;
}

void Server::stop() {
    if (!running_.load()) return;
    stop_requested_.store(true);
    uint64_t one = 1;
    ssize_t rc = write(wake_fd_, &one, sizeof(one));
    (void)rc;
    if (thread_.joinable()) thread_.join();
    // The reactor has exited: now the fds it waited on can close safely.
    close(listen_fd_);
    close(wake_fd_);
    close(epoll_fd_);
    listen_fd_ = wake_fd_ = epoll_fd_ = -1;
    running_.store(false);
}

void Server::post(std::function<void()> fn) {
    {
        std::lock_guard<std::mutex> lock(posted_mu_);
        posted_.push_back(std::move(fn));
    }
    uint64_t one = 1;
    ssize_t rc = write(wake_fd_, &one, sizeof(one));
    (void)rc;
}

void Server::call(std::function<void()> fn) {
    if (std::this_thread::get_id() == thread_.get_id()) {
        fn();
        return;
    }
    if (!running_.load()) {
        // Reactor joined (or never started): state is single-threaded now,
        // run inline instead of posting to a loop that will never drain.
        fn();
        return;
    }
    std::promise<void> done;
    auto fut = done.get_future();
    post([&fn, &done] {
        fn();
        done.set_value();
    });
    fut.wait();
}

size_t Server::kvmap_len() {
    size_t n = 0;
    call([&] { n = kv_->size(); });
    return n;
}

size_t Server::purge() {
    size_t n = 0;
    call([&] { n = kv_->purge(); });
    return n;
}

size_t Server::evict(double min_ratio, double max_ratio) {
    size_t n = 0;
    call([&] { n = kv_->evict(min_ratio, max_ratio); });
    return n;
}

double Server::usage() {
    double u = 0;
    call([&] { u = mm_->usage(); });
    return u;
}

std::string Server::stats_json() {
    std::string out;
    call([&] {
        out = "{\"kvmap_len\":" + std::to_string(kv_->size()) +
              ",\"usage\":" + std::to_string(mm_->usage()) +
              ",\"total_bytes\":" + std::to_string(mm_->total_bytes()) +
              ",\"used_bytes\":" + std::to_string(mm_->used_bytes()) +
              ",\"pools\":" + std::to_string(mm_->pool_count()) +
              ",\"pinned\":" + (mm_->pinned() ? std::string("true") : std::string("false")) +
              ",\"connections\":" + std::to_string(conns_.size()) +
              ",\"conns_accepted\":" + std::to_string(conns_accepted_) +
              ",\"spill\":{\"entries\":" + std::to_string(kv_->spilled_entries()) +
              ",\"bytes\":" + std::to_string(kv_->spilled_bytes()) +
              ",\"capacity\":" + std::to_string(kv_->spill_capacity()) +
              ",\"promotions\":" + std::to_string(kv_->spill_promotions()) +
              ",\"dropped\":" + std::to_string(kv_->spill_drops()) + "}" +
              // Two-class QoS scheduler counters (docs/qos.md): per-class
              // dispatch + slice counts, the scheduler's preempt/age
              // decisions, and the live suspended-op queue depths.
              ",\"qos\":{\"fg_ops\":" + std::to_string(qos_.fg_ops) +
              ",\"bg_ops\":" + std::to_string(qos_.bg_ops) +
              ",\"fg_slices\":" + std::to_string(qos_.fg_slices) +
              ",\"bg_slices\":" + std::to_string(qos_.bg_slices) +
              ",\"bg_preempted_slices\":" + std::to_string(qos_.bg_preempted) +
              ",\"bg_aged_slices\":" + std::to_string(qos_.bg_aged) +
              ",\"fg_queued\":" + std::to_string(cont_fg_.size()) +
              ",\"bg_queued\":" + std::to_string(cont_bg_.size()) +
              ",\"bg_cooldown_us\":" + std::to_string(config_.bg_cooldown_us) +
              ",\"bg_aging_us\":" + std::to_string(config_.bg_aging_us) + "}" +
              ",\"suspended_ops\":" + std::to_string(cont_fg_.size() + cont_bg_.size()) +
              // Descriptor-ring plane (docs/descriptor_ring.md): lifetime
              // attach/descriptor/doorbell/completion counters plus the
              // LIVE submission-ring depth (published-but-unconsumed) and
              // decoded-but-not-started pending depth across attached
              // conns. doorbells_rx vs descriptors is the submit-side
              // coalescing ratio the bench watches (one doorbell per doze,
              // not per op).
              ",\"ring\":{\"attached\":" + std::to_string(ring_counters_.attached) +
              ",\"conns\":" + std::to_string(ring_conns_.size()) +
              ",\"descriptors\":" + std::to_string(ring_counters_.descriptors) +
              ",\"doorbells_rx\":" + std::to_string(ring_counters_.doorbells_rx) +
              ",\"cq_doorbells_tx\":" + std::to_string(ring_counters_.cq_doorbells_tx) +
              ",\"completions\":" + std::to_string(ring_counters_.completions) +
              ",\"bad_descriptors\":" + std::to_string(ring_counters_.bad_descriptors) +
              ",\"torn_descriptors\":" + std::to_string(ring_counters_.torn_descriptors) +
              ",\"batch_slots\":" + std::to_string(ring_counters_.batch_slots) +
              ",\"batch_ops\":" + std::to_string(ring_counters_.batch_ops) +
              ",\"poll_hits\":" + std::to_string(ring_counters_.poll_hits) +
              ",\"poll_arms\":" + std::to_string(ring_counters_.poll_arms) +
              ",\"doorbell_elided\":" + std::to_string(ring_counters_.doorbell_elided) +
              ",\"sq_depth\":" + [this] {
                  uint64_t depth = 0;
                  for (Conn* rc : ring_conns_)
                      depth += ring_load_acq(&rc->ring->view.ctrl->sq_tail) -
                               rc->ring->sq_seq;
                  return std::to_string(depth);
              }() +
              ",\"pending\":" + [this] {
                  size_t pending = 0;
                  for (Conn* rc : ring_conns_)
                      pending += rc->ring->pending_fg.size() +
                                 rc->ring->pending_bg.size();
                  return std::to_string(pending);
              }() + "}" +
              // Reactor loop-pass phase accounting (docs/observability.md,
              // profiling section): where each pass's wall time went —
              // the native half of the continuous-profiling plane, the
              // per-phase denominator the /profile sampler's Python-side
              // frames do not see.
              ",\"prof\":{\"passes\":" + std::to_string(prof_.passes) +
              ",\"wait_us\":" + std::to_string(prof_.wait_us) +
              ",\"events_us\":" + std::to_string(prof_.events_us) +
              ",\"rings_us\":" + std::to_string(prof_.rings_us) +
              ",\"slices_us\":" + std::to_string(prof_.slices_us) +
              ",\"poll_us\":" + std::to_string(prof_.poll_us) +
              ",\"other_us\":" + std::to_string(prof_.other_us) + "}" +
              // Server-side trace tick ring (docs/observability.md): the
              // manage plane's /trace endpoint joins these to client spans
              // by trace id; recorded/dropped size the ring's coverage.
              ",\"trace\":{\"recorded\":" + std::to_string(trace_next_) +
              ",\"dropped\":" + std::to_string(trace_dropped_) +
              ",\"entries\":[";
        uint64_t t0 = trace_next_ > kTraceRing ? trace_next_ - kTraceRing : 0;
        for (uint64_t i = t0; i < trace_next_; i++) {
            const TraceTick& t = trace_ring_[i % kTraceRing];
            if (i != t0) out += ",";
            out += "{\"trace_id\":" + std::to_string(t.trace_id) +
                   ",\"parent_id\":" + std::to_string(t.parent_id) +
                   ",\"op\":\"" + std::string(1, static_cast<char>(t.op)) + "\"" +
                   ",\"prio\":" + std::to_string(t.prio) +
                   ",\"ok\":" + std::to_string(t.ok ? 1 : 0) +
                   ",\"recv_us\":" + std::to_string(t.recv_us) +
                   ",\"first_slice_us\":" + std::to_string(t.first_us) +
                   ",\"last_slice_us\":" + std::to_string(t.last_us) +
                   ",\"done_us\":" + std::to_string(t.done_us) +
                   ",\"bytes\":" + std::to_string(t.bytes) + "}";
        }
        out += "]},\"ops\":{";
        bool first = true;
        for (const auto& [op, s] : stats_) {
            if (!first) out += ",";
            first = false;
            out += "\"" + std::string(1, static_cast<char>(op)) + "\":{" +
                   "\"count\":" + std::to_string(s.count) +
                   ",\"errors\":" + std::to_string(s.errors) +
                   ",\"bytes_in\":" + std::to_string(s.bytes_in) +
                   ",\"bytes_out\":" + std::to_string(s.bytes_out) +
                   ",\"total_us\":" + std::to_string(s.total_us) +
                   ",\"p50_us\":" + std::to_string(s.p50_us()) +
                   ",\"p99_us\":" + std::to_string(s.p99_us()) +
                   // Sparse non-empty latency buckets as [le_us, count]
                   // pairs (le inclusive; base-2 octaves, 32 sub-buckets =
                   // ~2% resolution) — the /metrics exporter renders the
                   // cumulative infinistore_op_duration_us histogram from
                   // these, and the p50/p99 gauges above are derived from
                   // the same buckets.
                   ",\"hist_us\":[";
            bool hfirst = true;
            for (int b = 0; b < OpStats::kBuckets; b++) {
                if (s.lat_buckets[b] == 0) continue;
                if (!hfirst) out += ",";
                hfirst = false;
                out += "[" + std::to_string(OpStats::bucket_le_us(b)) + "," +
                       std::to_string(s.lat_buckets[b]) + "]";
            }
            out += "]}";
        }
        out += "}}";
    });
    return out;
}

void Server::loop() {
    constexpr int kMaxEvents = 64;
    epoll_event events[kMaxEvents];
    // Consecutive event-free ticks with sliced work pending (see
    // run_cont_pass for how the streak boosts a lone suspended op).
    int idle_streak = 0;
    while (!stop_requested_.load(std::memory_order_relaxed)) {
        uint64_t pass_t0 = now_us();
        // Pending sliced ops: poll without blocking so their next slice runs
        // right after any ready events (fairness: events first, then
        // slices). Exception: when the ONLY pending work is background
        // slices currently deferred by the foreground cooldown, sleep ~1ms
        // instead of spinning — a busy-polling reactor would burn the
        // single core exactly while the foreground wave it deferred FOR is
        // still running (events still interrupt the sleep instantly, and
        // the aging clock tolerates millisecond granularity).
        int timeout = 200;
        if (!cont_fg_.empty()) {
            timeout = 0;
        } else if (!cont_bg_.empty()) {
            timeout =
                now_us() - last_fg_us_ < config_.bg_cooldown_us ? 1 : 0;
        }
        uint64_t poll_spent = 0;
        if (timeout != 0 && !ring_conns_.empty()) {
            // Adaptive pre-park poll (docs/descriptor_ring.md): while
            // descriptors have been arriving on a fast cadence, busy-poll
            // the submission tails for ~2x the smoothed inter-arrival gap
            // before parking — a hit consumes the next flush with no
            // doorbell frame and no epoll round-trip. The window is gated
            // on a RECENT arrival, so a connection going quiet ages out of
            // polling within kRingPollRecentUs and the reactor dozes at
            // zero CPU. Socket traffic cuts the window short via a
            // zero-timeout epoll peek (level-triggered: the main wait
            // below re-reports whatever the peek saw).
            uint64_t poll_t0 = now_us();
            uint64_t budget =
                (ring_last_desc_us_ != 0 &&
                 poll_t0 - ring_last_desc_us_ <= kRingPollRecentUs)
                    ? ring_poll_budget(ring_gap_ewma_us_)
                    : 0;
            if (budget != 0) {
                uint64_t deadline = poll_t0 + budget;
                bool hit = false;
                while (!stop_requested_.load(std::memory_order_relaxed)) {
                    for (Conn* rc : ring_conns_) {
                        if (ring_load_acq(&rc->ring->view.ctrl->sq_tail) !=
                            rc->ring->sq_seq) {
                            hit = true;
                            break;
                        }
                    }
                    if (hit) break;
                    epoll_event peek;
                    if (epoll_wait(epoll_fd_, &peek, 1, 0) > 0) break;
                    if (now_us() >= deadline) break;
                    // Mandatory on a shared core: the client thread we are
                    // polling against needs cycles to publish.
                    std::this_thread::yield();
                }
                if (hit) {
                    ring_counters_.poll_hits++;
                    timeout = 0;
                } else {
                    ring_counters_.poll_arms++;
                }
                poll_spent = now_us() - poll_t0;
            }
        }
        if (timeout != 0 && !ring_conns_.empty()) {
            // About to block: park on every attached submission ring, then
            // re-check the tails — the Dekker pairing with the client's
            // descriptor publish + flag read guarantees either we see the
            // new tail here or the client sends a doorbell frame.
            for (Conn* rc : ring_conns_)
                ring_flag_park(&rc->ring->view.ctrl->srv_waiting);
            ring_fence();
            for (Conn* rc : ring_conns_) {
                if (ring_load_acq(&rc->ring->view.ctrl->sq_tail) !=
                    rc->ring->sq_seq) {
                    timeout = 0;
                    break;
                }
            }
            if (timeout == 0)
                for (Conn* rc : ring_conns_)
                    ring_flag_clear(&rc->ring->view.ctrl->srv_waiting);
        }
        uint64_t wait_t0 = now_us();
        int n = epoll_wait(epoll_fd_, events, kMaxEvents, timeout);
        uint64_t wait_t1 = now_us();
        for (Conn* rc : ring_conns_)
            ring_flag_clear(&rc->ring->view.ctrl->srv_waiting);
        if (n < 0) {
            if (errno == EINTR) {
                // The interrupted pass still blocked in epoll — book it,
                // or a signal-heavy host undercounts the wait fraction
                // the busy-poll-vs-eventfd receipt reads.
                prof_.passes++;
                prof_.wait_us += wait_t1 - wait_t0;
                prof_.poll_us += poll_spent;
                prof_.other_us += wait_t0 - pass_t0 - poll_spent;
                continue;
            }
            ITS_LOG_ERROR("epoll_wait: %s", strerror(errno));
            break;
        }
        for (int i = 0; i < n; i++) {
            int fd = events[i].data.fd;
            if (fd == listen_fd_) {
                accept_ready();
            } else if (fd == wake_fd_) {
                uint64_t buf;
                while (read(wake_fd_, &buf, sizeof(buf)) > 0) {
                }
                std::vector<std::function<void()>> fns;
                {
                    std::lock_guard<std::mutex> lock(posted_mu_);
                    fns.swap(posted_);
                }
                for (auto& fn : fns) fn();
            } else {
                auto it = conns_.find(fd);
                if (it == conns_.end()) continue;
                Conn* c = it->second.get();
                if (events[i].events & (EPOLLHUP | EPOLLERR)) {
                    close_conn(c);
                    continue;
                }
                if (events[i].events & EPOLLOUT) conn_writable(c);
                // conn_writable may close on error; re-check liveness.
                if (!c->dead && (events[i].events & EPOLLIN)) conn_readable(c);
            }
        }
        uint64_t events_t1 = now_us();
        drain_rings();
        uint64_t rings_t1 = now_us();
        run_cont_pass(n, &idle_streak);
        uint64_t slices_t1 = now_us();
        graveyard_.clear();
        // Phase ledger (docs/observability.md): the pass's wall time
        // attributed to wait / event dispatch / ring drain / cont slices,
        // with the pre-wait bookkeeping (timeout calc, ring park) and the
        // graveyard sweep under "other".
        prof_.passes++;
        prof_.wait_us += wait_t1 - wait_t0;
        prof_.events_us += events_t1 - wait_t1;
        prof_.rings_us += rings_t1 - events_t1;
        prof_.slices_us += slices_t1 - rings_t1;
        prof_.poll_us += poll_spent;
        prof_.other_us += (wait_t0 - pass_t0 - poll_spent) + (now_us() - slices_t1);
    }
    // Drain control closures posted during shutdown so no caller hangs.
    {
        std::vector<std::function<void()>> fns;
        {
            std::lock_guard<std::mutex> lock(posted_mu_);
            fns.swap(posted_);
        }
        for (auto& fn : fns) fn();
    }
    // Teardown on the reactor thread: connection fds only. The listen/wake/
    // epoll fds are closed by stop() AFTER the join — stop() writes to
    // wake_fd_ to interrupt this loop, and closing it here would race that
    // write (a recycled fd number could receive the byte; TSAN-caught).
    for (auto& [fd, c] : conns_) close(fd);
    conns_.clear();
}

void Server::accept_ready() {
    while (true) {
        int fd = accept4(listen_fd_, nullptr, nullptr, SOCK_NONBLOCK | SOCK_CLOEXEC);
        if (fd < 0) return;
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // No explicit SO_SNDBUF/SO_RCVBUF: setting them disables kernel
        // autotuning, which reaches tcp_rmem max (32MB here) and measures
        // ~30% faster than a fixed 4MB clamp on the loopback batched bench.
        set_pacing_rate(fd, config_.pacing_rate_mbps, "server accept");
        auto conn = std::make_unique<Conn>();
        conn->fd = fd;
        epoll_event ev{};
        ev.events = EPOLLIN;
        ev.data.fd = fd;
        epoll_ctl(epoll_fd_, EPOLL_CTL_ADD, fd, &ev);
        conns_.emplace(fd, std::move(conn));
        conns_accepted_++;
        ITS_LOG_DEBUG("accepted connection fd=%d", fd);
    }
}

void Server::close_conn(Conn* c) {
    if (c->dead) return;
    c->dead = true;
    if (c->cont != nullptr) {
        cont_fg_.erase(std::remove(cont_fg_.begin(), cont_fg_.end(), c),
                       cont_fg_.end());
        cont_bg_.erase(std::remove(cont_bg_.begin(), cont_bg_.end(), c),
                       cont_bg_.end());
    }
    if (c->ring != nullptr)
        ring_conns_.erase(std::remove(ring_conns_.begin(), ring_conns_.end(), c),
                          ring_conns_.end());
    epoll_ctl(epoll_fd_, EPOLL_CTL_DEL, c->fd, nullptr);
    close(c->fd);
    auto it = conns_.find(c->fd);
    if (it != conns_.end()) {
        graveyard_.push_back(std::move(it->second));
        conns_.erase(it);
    }
}

void Server::queue_cont(Conn* c) {
    if (!c->queued_cont) {
        bool bg = c->cont != nullptr && c->cont->prio == kPriorityBackground;
        (bg ? cont_bg_ : cont_fg_).push_back(c);
        c->queued_cont = true;
    }
}

// Pop + run one budget slice for the conn at the front of ``queue``,
// re-queueing it (by its op's class) when more slices remain.
void Server::run_one_slice(Conn* c, std::deque<Conn*>* queue) {
    queue->pop_front();
    c->queued_cont = false;
    if (c->dead || c->cont == nullptr) return;
    (c->cont->prio == kPriorityBackground ? qos_.bg_slices : qos_.fg_slices)++;
    run_cont_slice(c);
    if (!c->dead && c->cont != nullptr) queue_cont(c);
}

void Server::note_op(uint8_t prio) {
    qos_.note(prio);
    if (prio != kPriorityBackground) last_fg_us_ = now_us();
}

// ---------------------------------------------------------------------------
// Trace ticks (docs/observability.md). Begin on dispatch of a traced op,
// slice on every unit of payload/slice work, finish where the op's stats
// record — pushing {recv, first_slice, last_slice, done} into the ring the
// manage plane's /trace endpoint joins to client spans by trace id.
// ---------------------------------------------------------------------------

void Server::trace_begin(Conn* c, uint64_t trace_id, uint64_t parent,
                         uint8_t prio) {
    c->trace_id = trace_id;
    if (trace_id == 0) return;
    c->trace_parent = parent;
    c->trace_prio = prio;
    c->trace_first_us = 0;
    c->trace_last_us = 0;
}

void Server::trace_slice(Conn* c) {
    if (c->trace_id == 0) return;
    uint64_t now = now_us();
    if (c->trace_first_us == 0) c->trace_first_us = now;
    c->trace_last_us = now;
}

void Server::trace_finish(Conn* c, uint64_t bytes, bool ok) {
    if (c->trace_id == 0) return;
    TraceTick& t = trace_ring_[trace_next_ % kTraceRing];
    if (trace_next_ >= kTraceRing) trace_dropped_++;
    t.trace_id = c->trace_id;
    t.parent_id = c->trace_parent;
    t.op = c->cur_op;
    t.prio = static_cast<uint8_t>(c->trace_prio);
    t.ok = ok;
    t.recv_us = c->op_start_us;
    t.first_us = c->trace_first_us;
    t.last_us = c->trace_last_us;
    t.done_us = now_us();
    t.bytes = bytes;
    trace_next_++;
    c->trace_id = 0;
    c->trace_parent = 0;
}

bool Server::bg_must_defer() const {
    return !cont_fg_.empty() || now_us() - last_fg_us_ < config_.bg_cooldown_us;
}

// ---------------------------------------------------------------------------
// Descriptor-ring copy engine (docs/descriptor_ring.md). Submission rings
// are drained every loop pass: descriptors validate and queue per-conn by
// QoS class, then ride the SAME budget-sliced SegCont machinery as socket
// segment ops — fg-first scheduling, bg cooldown/aging, trace ticks, and
// the op-latency histograms all behave identically; only the completion
// leaves over the ring.
// ---------------------------------------------------------------------------

void Server::drain_rings() {
    uint64_t before = ring_counters_.descriptors;
    for (size_t i = 0; i < ring_conns_.size();) {
        Conn* c = ring_conns_[i];
        if (!drain_ring_conn(c)) {
            // Torn/corrupt descriptor: the ring is untrustworthy — close
            // the connection (the client fails over / reconnects).
            close_conn(c);
        } else {
            start_ring_descs(c);
        }
        // Either call can close_conn (CQE overflow inside the drain, error
        // CQE on a bad descriptor), which erases c from ring_conns_ — then
        // the element at i is already the NEXT conn and i must not advance.
        if (i < ring_conns_.size() && ring_conns_[i] == c) i++;
    }
    // Feed the adaptive pre-park poll: a pass that consumed descriptors
    // stamps the arrival EWMA (ring.h ring_gap_note) the next park reads.
    if (ring_counters_.descriptors != before)
        ring_gap_note(&ring_gap_ewma_us_, &ring_last_desc_us_, now_us());
}

bool Server::drain_ring_conn(Conn* c) {
    Conn::RingSrv& r = *c->ring;
    uint64_t tail = ring_load_acq(&r.view.ctrl->sq_tail);
    while (r.sq_seq < tail) {
        // Decoded-but-not-started descriptors are bounded by the ring
        // depth: a CONFORMING client caps in-flight ops at cq_slots, so
        // hitting this means a hostile/buggy peer is refilling freed slots
        // without waiting for completions. Stop consuming (sq_head stays
        // put — the natural backpressure) instead of growing an unbounded
        // heap queue; draining resumes as pending ops start.
        if (r.pending_fg.size() + r.pending_bg.size() >= r.view.cq_slots)
            break;
        RingSlot* s = r.view.slot(r.sq_seq);
        if (ring_load_acq(&s->gen) != r.sq_seq + 1) {
            // The publish discipline stores gen before tail, so a mismatch
            // under an advanced tail is a torn or corrupt descriptor.
            ring_counters_.torn_descriptors++;
            ITS_LOG_WARN("ring: torn descriptor at seq %llu fd=%d, closing",
                         static_cast<unsigned long long>(r.sq_seq), c->fd);
            return false;
        }
        uint8_t op = s->op;
        uint64_t token = s->token;
        uint32_t meta_len = s->meta_len;
        if (s->flags & kRingSlotFlagBatch) {
            // Multi-op batch slot: RingBatchHdr + count x (RingBatchEntry +
            // SegBatchMeta). Op k completes under token + k. The whole slot
            // is validated before any op is queued; a malformed slot
            // error-CQEs every token the client parked against it (when the
            // header itself is unreadable, only the base token — there is
            // nothing trustworthy to size the group by).
            const uint8_t* arena =
                reinterpret_cast<const uint8_t*>(r.view.meta_at(r.sq_seq));
            uint16_t cnt = 0;
            bool ok = meta_len >= sizeof(RingBatchHdr) &&
                      meta_len <= r.view.meta_stride;
            if (ok) {
                RingBatchHdr hdr;
                memcpy(&hdr, arena, sizeof(hdr));
                cnt = hdr.count;
                ok = cnt >= 1 && cnt <= kRingBatchMaxOps;
                if (!ok) cnt = 0;  // header untrustworthy
            }
            std::vector<Conn::RingSrv::PendingDesc> decoded;
            if (ok) {
                decoded.reserve(cnt);
                size_t off = sizeof(RingBatchHdr);
                for (uint16_t k = 0; k < cnt && ok; k++) {
                    RingBatchEntry ent;
                    if (off + sizeof(ent) > meta_len) {
                        ok = false;
                        break;
                    }
                    memcpy(&ent, arena + off, sizeof(ent));
                    off += sizeof(ent);
                    ok = (ent.op == kOpPutFrom || ent.op == kOpGetInto) &&
                         ent.meta_len <= meta_len - off;
                    if (!ok) break;
                    try {
                        SegBatchMeta m = SegBatchMeta::decode(arena + off, ent.meta_len);
                        decoded.push_back(
                            Conn::RingSrv::PendingDesc{ent.op, token + k, std::move(m)});
                    } catch (const std::exception&) {
                        ok = false;
                        break;
                    }
                    off += ent.meta_len;
                }
            }
            r.sq_seq++;
            ring_store_rel(&r.view.ctrl->sq_head, r.sq_seq);
            if (!ok) {
                uint64_t fail = cnt != 0 ? cnt : 1;
                ring_counters_.descriptors += fail;
                ring_counters_.bad_descriptors += fail;
                for (uint64_t k = 0; k < fail && !c->dead; k++)
                    ring_push_cqe(c, token + k, kStatusInvalidReq, 0);
                if (c->dead) return true;  // cqe overflow closed it
                continue;
            }
            ring_counters_.descriptors += cnt;
            ring_counters_.batch_slots++;
            ring_counters_.batch_ops += cnt;
            for (auto& d : decoded) {
                auto& q = d.m.priority == kPriorityBackground ? r.pending_bg
                                                              : r.pending_fg;
                q.push_back(std::move(d));
            }
            continue;
        }
        SegBatchMeta m;
        bool ok = (op == kOpPutFrom || op == kOpGetInto) &&
                  meta_len <= r.view.meta_stride;
        if (ok) {
            try {
                m = SegBatchMeta::decode(
                    reinterpret_cast<const uint8_t*>(r.view.meta_at(r.sq_seq)),
                    meta_len);
            } catch (const std::exception&) {
                ok = false;
            }
        }
        // Slot consumed: advance the head so the client can reuse it (the
        // decoded copy above is ours now) — this is the backpressure relief
        // that keeps a deep pipeline posting while ops are still running.
        r.sq_seq++;
        ring_store_rel(&r.view.ctrl->sq_head, r.sq_seq);
        ring_counters_.descriptors++;
        if (!ok) {
            ring_counters_.bad_descriptors++;
            ring_push_cqe(c, token, kStatusInvalidReq, 0);
            if (c->dead) return true;  // cqe overflow closed it
            continue;
        }
        auto& q = m.priority == kPriorityBackground ? r.pending_bg : r.pending_fg;
        q.push_back(Conn::RingSrv::PendingDesc{op, token, std::move(m)});
    }
    return true;
}

// Feed pending descriptors into the conn's single continuation slot —
// foreground before background (a bg descriptor never heads-of-line a
// later fg one), FIFO within a class. Invalid descriptors complete with an
// error CQE right here and the loop moves on.
void Server::start_ring_descs(Conn* c) {
    while (!c->dead && c->cont == nullptr && c->rstate == Conn::RState::kHeader &&
           c->hdr_got == 0) {
        Conn::RingSrv& r = *c->ring;
        auto& q = !r.pending_fg.empty() ? r.pending_fg : r.pending_bg;
        if (q.empty()) return;
        Conn::RingSrv::PendingDesc d = std::move(q.front());
        q.pop_front();
        start_ring_desc(c, d.op, d.token, std::move(d.m));
    }
}

void Server::start_ring_desc(Conn* c, uint8_t op, uint64_t token, SegBatchMeta m) {
    c->cur_op = op;
    c->op_start_us = now_us();
    trace_begin(c, m.trace_id, m.trace_parent, m.priority);
    size_t n = m.keys.size();
    auto seg_it = c->segments.find(m.seg_id);
    uint32_t status = kStatusOk;
    // Same validation the socket dispatch runs (handle_shm PutFrom/GetInto).
    if (n == 0 || m.block_size == 0 || n != m.offsets.size() ||
        seg_it == c->segments.end()) {
        status = kStatusInvalidReq;
    } else {
        const Conn::SegMap& seg = seg_it->second;
        for (uint64_t off : m.offsets) {
            if (off > seg.size || m.block_size > seg.size - off) {
                status = kStatusInvalidReq;
                break;
            }
        }
        if (status == kStatusOk && op == kOpGetInto) {
            for (const auto& key : m.keys) {
                if (!kv_->exists(key)) {
                    status = kStatusKeyNotFound;
                    break;
                }
            }
        }
    }
    if (status != kStatusOk) {
        stats_[op].record(now_us() - c->op_start_us, 0, 0, false);
        trace_finish(c, 0, false);
        ring_push_cqe(c, token, status, 0);
        return;
    }
    note_op(m.priority);
    auto cont = std::make_unique<Conn::SegCont>();
    cont->op = op;
    cont->prio = m.priority;
    cont->m = std::move(m);
    if (op == kOpGetInto) cont->phase = Conn::SegCont::Phase::kPin;
    cont->blocks.reserve(n);
    cont->from_ring = true;
    cont->ring_token = token;
    c->cont = std::move(cont);
    suspend_for_cont(c);  // slices run in this pass's run_cont_pass
}

void Server::ring_push_cqe(Conn* c, uint64_t token, uint32_t status, uint64_t bytes) {
    Conn::RingSrv& r = *c->ring;
    if (r.cq_seq - ring_load_acq(&r.view.ctrl->cq_head) >= r.view.cq_slots) {
        // The client bounds in-flight ring ops to cq_slots, so this can
        // only happen with a broken/hostile client: fail the connection
        // rather than overwrite an unconsumed completion.
        ITS_LOG_WARN("ring: completion ring overflow fd=%d, closing", c->fd);
        close_conn(c);
        return;
    }
    RingCqe* e = r.view.cqe(r.cq_seq);
    e->token = token;
    e->bytes = bytes;
    e->status = status;
    e->flags = 0;
    ring_store_rel(&e->gen, r.cq_seq + 1);
    r.cq_seq++;
    ring_store_rel(&r.view.ctrl->cq_tail, r.cq_seq);
    ring_counters_.completions++;
    ring_fence();
    if (ring_flag_take(&r.view.ctrl->cli_waiting)) {
        // The client reactor parked: one 16-byte doorbell frame wakes it;
        // completions landing while it is awake piggyback silently.
        ring_counters_.cq_doorbells_tx++;
        send_resp(c, kStatusRingEvent, {}, {}, {});
    } else {
        // The client is awake — inside its adaptive poll window or already
        // draining — so this completion needed no doorbell frame at all:
        // the elision the small-op fast path banks on.
        ring_counters_.doorbell_elided++;
    }
}

// Completion of a ring-sourced continuation: stats + trace tick close like
// the socket path, then a CQE instead of a response frame — and the next
// pending descriptor starts immediately (same tick, no doorbell needed).
void Server::ring_finish(Conn* c, uint32_t status, uint64_t bytes) {
    uint64_t token = c->cont->ring_token;
    uint8_t op = c->cont->op;
    bool ok = status == kStatusOk;
    stats_[op].record(now_us() - c->op_start_us, op == kOpPutFrom ? bytes : 0,
                      op == kOpGetInto ? bytes : 0, ok);
    trace_finish(c, bytes, ok);
    c->cont.reset();
    arm_read(c, true);
    c->reset_read();
    ring_push_cqe(c, token, status, bytes);
    if (!c->dead) start_ring_descs(c);
}

// One scheduling pass over the suspended sliced ops, run after each tick's
// epoll events (fairness: events first, then slices).
//
// Two-level discipline: FOREGROUND conts round-robin one slice each — with
// no background op suspended this is EXACTLY the pre-QoS single-queue
// behavior. BACKGROUND conts run a full round-robin only while foreground
// is quiet: no foreground slice pending AND no foreground op seen within
// the last bg_cooldown_us (the wave hysteresis — a decode wave's reads
// arrive microseconds apart, and resuming background between them would
// land its slices, and its completion wakeups, inside the wave).
// While deferred, background still gets ONE slice per bg_aging_us — the
// time-based, starvation-proof aging escape: background always makes
// >= slice_bytes per bg_aging_us of progress, so it drains under ANY
// foreground flood.
//
// Idle-streak boost (pre-existing): slicing costs ~6% of solo batch
// throughput in loop overhead; with exactly one suspended op and a streak
// of event-free polls, run up to 1+streak slices back-to-back. For a
// BACKGROUND cont each extra boost round first peeks epoll with zero
// timeout and stops on any ready event — a foreground request arriving
// mid-boost waits at most one slice, not the whole burst (level-triggered
// epoll re-reports the peeked event to the main loop).
void Server::run_cont_pass(int events_seen, int* idle_streak) {
    size_t total = cont_fg_.size() + cont_bg_.size();
    if (total == 0) {
        *idle_streak = 0;
        idle_streak_ = 0;
        return;
    }
    *idle_streak = events_seen == 0 ? std::min(*idle_streak + 1, 8) : 0;
    idle_streak_ = *idle_streak;  // run_cont_slice's ring budget reads this
    // A solo RING cont spends the idle boost on slice SIZE (one big slice,
    // see run_cont_slice) instead of slice COUNT — same per-tick work and
    // preemption bound, far less per-slice overhead.
    Conn* solo = total == 1
                     ? (cont_fg_.empty() ? cont_bg_.front() : cont_fg_.front())
                     : nullptr;
    bool ring_solo =
        solo != nullptr && solo->cont != nullptr && solo->cont->from_ring;
    int rounds = 1 + (total == 1 && !ring_solo ? *idle_streak : 0);
    for (int r = 0; r < rounds && !(cont_fg_.empty() && cont_bg_.empty()); r++) {
        if (r > 0 && !cont_bg_.empty()) {
            epoll_event peek;
            if (epoll_wait(epoll_fd_, &peek, 1, 0) > 0) break;
        }
        uint64_t now = now_us();
        bool fg_pending = !cont_fg_.empty();
        if (fg_pending) last_fg_us_ = now;
        for (size_t i = 0, n0 = cont_fg_.size(); i < n0 && !cont_fg_.empty(); i++)
            run_one_slice(cont_fg_.front(), &cont_fg_);
        if (cont_bg_.empty()) continue;
        if (fg_pending || now - last_fg_us_ < config_.bg_cooldown_us) {
            if (now - last_bg_slice_us_ >= config_.bg_aging_us) {
                qos_.bg_aged++;
                last_bg_slice_us_ = now;
                run_one_slice(cont_bg_.front(), &cont_bg_);
            } else {
                // One per deferred pass (a pass is one slice slot background
                // sat out), NOT per queued conn — the loop spins fast while
                // foreground slices run, and multiplying by queue depth
                // would inflate the counter by orders of magnitude.
                qos_.bg_preempted++;
            }
        } else {
            last_bg_slice_us_ = now;
            for (size_t i = 0, n0 = cont_bg_.size(); i < n0 && !cont_bg_.empty(); i++)
                run_one_slice(cont_bg_.front(), &cont_bg_);
        }
    }
}

void Server::suspend_for_cont(Conn* c) {
    c->rstate = Conn::RState::kSuspended;
    arm_read(c, false);  // the next pipelined request waits in the kernel
    queue_cont(c);
}

// One budget slice of a suspended PutAlloc. Fast path: the whole remaining
// allocation in one call (free-RAM case completes in the first slice).
// Under pressure: bank a budget-sized chunk per slice — banked BlockRefs
// cannot be stolen by concurrent allocators, so progress is monotone.
void Server::run_putalloc_slice(Conn* c) {
    trace_slice(c);
    Conn::SegCont& ct = *c->cont;
    const size_t n = ct.m.keys.size();
    const size_t bs = ct.m.block_size;
    const size_t budget_blocks = std::max<size_t>(1, config_.slice_bytes / bs);
    size_t remaining = n - ct.blocks.size();
    if (remaining > 0) {
        std::vector<Lease> leases;
        bool ok, capped_full;
        {
            SliceBudget budget(this, budget_blocks);
            ok = alloc_blocks(bs, remaining, &leases);
            capped_full = slice_capped_;
            if (!ok && remaining > budget_blocks) {
                // Bank what a budget-sized chunk can get right now.
                ok = alloc_blocks(bs, std::min(budget_blocks, remaining), &leases);
            }
        }
        if (!ok) {
            if (capped_full || slice_capped_) return;  // retry next tick
            // Reclaim ran dry: genuine 507 (banked blocks free via refs).
            finish_cont(c, kStatusOutOfMemory);
            return;
        }
        for (const auto& lease : leases)
            ct.blocks.push_back(std::make_shared<Block>(mm_.get(), lease.ptr, lease.size));
        if (ct.blocks.size() < n) return;
    }
    // Fully allocated: resolve locations against the CURRENT directory
    // (allocation may have auto-extended a pool) and reply.
    auto dir = mm_->pool_dir();
    ShmLocResp resp;
    resp.ticket = c->next_ticket++;
    resp.locs.reserve(n);
    bool mappable = true;
    for (const auto& b : ct.blocks) {
        PoolLoc loc;
        mappable = mappable && shm_mappable(b->data(), dir, &loc);
        resp.locs.push_back(
            ShmLoc{loc.pool_id, loc.offset, static_cast<uint32_t>(bs)});
    }
    if (!mappable) {
        // Blocks landed in an anonymous-fallback pool: tell the client to
        // retry over the socket path (BlockRefs free the leases).
        finish_cont(c, kStatusRetry);
        return;
    }
    Conn::PendingPut pending;
    pending.keys = std::move(ct.m.keys);
    pending.start_us = c->op_start_us;
    pending.blocks = std::move(ct.blocks);
    c->pending_puts.emplace(resp.ticket, std::move(pending));
    // The tick spans the alloc leg (the client memcpy + commit are their
    // own untraced wire ops); the op-latency stat still spans alloc->commit.
    trace_finish(c, 0, true);
    c->cont.reset();
    arm_read(c, true);
    send_loc_resp(c, resp, dir);
}

void Server::finish_cont(Conn* c, uint32_t status) {
    // Error exit: uncommitted blocks free via BlockRef; nothing touched the
    // client segment yet on any failing path (alloc/pin precede copies).
    if (c->cont->from_ring) {
        ring_finish(c, status, 0);
        return;
    }
    stats_[c->cont->op].record(now_us() - c->op_start_us, 0, 0, false);
    c->cont.reset();
    arm_read(c, true);
    c->reset_read();
    send_status(c, status);
}

// Shared promote+pin slice (GetLoc and GetInto's pin phase). The budget
// charges ACTUAL promotion work (each promotion = a spill read + possibly a
// demote), not key count: a fully RAM-resident batch is all O(1) LRU
// touches and completes in its first slice — the same reactor tick as its
// dispatch — while spill-heavy batches yield every ~half byte-budget of
// promotions. Pins persist in the continuation, so progress is monotone:
// the op completes, or reclaim genuinely runs dry (its own pins exceed
// RAM) and 507s — never a retry livelock.
Server::PinResult Server::pin_slice(
    Conn* c, const std::function<bool(size_t, const BlockRef&)>& validate) {
    Conn::SegCont& ct = *c->cont;
    const size_t n = ct.m.keys.size();
    const size_t budget_blocks =
        std::max<size_t>(1, config_.slice_bytes / ct.m.block_size);
    const size_t promote_cap = std::max<size_t>(1, budget_blocks / 2);
    // Resident gets are ~free but not literally free; cap touches per slice
    // so a huge resident batch still yields within ~tens of microseconds.
    const size_t touch_cap = std::max<size_t>(256, budget_blocks);
    const uint64_t p0 = kv_->spill_promotions();
    size_t touched = 0;
    SliceBudget budget(this, budget_blocks);
    while (ct.idx < n) {
        if (kv_->spill_promotions() - p0 >= promote_cap || touched >= touch_cap)
            return PinResult::kYield;  // slice's work done; pins kept
        BlockRef b = kv_->get(ct.m.keys[ct.idx]);  // LRU touch; promotes
        touched++;
        if (b == nullptr) {
            if (!kv_->exists(ct.m.keys[ct.idx])) {
                // Deleted between slices: a miss, not pressure (checked
                // before slice_capped_ — a plain map miss leaves the flag
                // stale).
                finish_cont(c, kStatusKeyNotFound);
                return PinResult::kFinished;
            }
            if (slice_capped_) return PinResult::kYield;  // pins kept
            // Reclaim ran dry with the key still spilled: the key is cold
            // but ALIVE (typically this op's own pins exceed RAM) — the
            // typed 512, so callers can tell "retry smaller / read via the
            // cold tier" from genuine allocation exhaustion (507).
            finish_cont(c, kStatusColdTier);
            return PinResult::kFinished;
        }
        if (!validate(ct.idx, b)) {
            finish_cont(c, kStatusInvalidReq);
            return PinResult::kFinished;
        }
        ct.blocks.push_back(std::move(b));
        ct.idx++;
    }
    return PinResult::kDone;
}

// One budget slice of a suspended GetLoc (see pin_slice for the budget
// discipline).
void Server::run_getloc_slice(Conn* c) {
    trace_slice(c);
    Conn::SegCont& ct = *c->cont;
    const size_t bs = ct.m.block_size;
    if (pin_slice(c, [bs](size_t, const BlockRef& b) {
            return b->size() <= bs;
        }) != PinResult::kDone) {
        return;
    }
    // All pinned: resolve locations against the CURRENT pool directory
    // (promotion may have auto-extended a pool) and reply.
    auto dir = mm_->pool_dir();
    ShmLocResp resp;
    resp.ticket = c->next_ticket++;
    uint64_t total = 0;
    for (const auto& b : ct.blocks) {
        PoolLoc loc;
        if (!shm_mappable(b->data(), dir, &loc)) {
            // Block lives in an anonymous-fallback pool; the client must
            // fetch over the socket path.
            finish_cont(c, kStatusRetry);
            return;
        }
        resp.locs.push_back(
            ShmLoc{loc.pool_id, loc.offset, static_cast<uint32_t>(b->size())});
        total += b->size();
    }
    c->pending_gets.emplace(resp.ticket, std::move(ct.blocks));
    stats_[kOpGetLoc].record(now_us() - c->op_start_us, 0, total, true);
    trace_finish(c, total, true);
    c->cont.reset();
    arm_read(c, true);
    send_loc_resp(c, resp, dir);
}

// One budget slice of a suspended segment op. Phases keep the original
// all-or-nothing contract: PutFrom allocates everything before copying or
// committing anything; GetInto pins (promotes) everything before the first
// segment write — a 507/400 can therefore still abort cleanly mid-op.
void Server::run_cont_slice(Conn* c) {
    Conn::SegCont& ct = *c->cont;
    if (ct.op == kOpPutAlloc) {
        run_putalloc_slice(c);
        return;
    }
    if (ct.op == kOpGetLoc) {
        run_getloc_slice(c);
        return;
    }
    auto seg_it = c->segments.find(ct.m.seg_id);
    if (seg_it == c->segments.end()) {  // unreachable: validated at dispatch
        finish_cont(c, kStatusInvalidReq);
        return;
    }
    const Conn::SegMap& seg = seg_it->second;
    const size_t n = ct.m.keys.size();
    const size_t bs = ct.m.block_size;
    // Adaptive slice budget for ring-sourced ops (docs/descriptor_ring.md):
    // when this is the ONLY pending sliced op and the loop has seen
    // event-free polls (idle_streak_), grow the quantum exponentially up to
    // 32x (4MB at the default 128KB) — per-slice fixed cost (queue churn,
    // clock reads, loop overhead) was the dominant non-copy term inside
    // first_slice->last_slice. Any epoll event resets the streak, so a
    // contending request waits at most one boosted slice (~300us at
    // streaming-store bandwidth, see streamcopy.h). Socket conts keep the
    // exact legacy budget (off-path behavior unchanged).
    size_t eff_slice_bytes = config_.slice_bytes;
    if (ct.from_ring && cont_fg_.empty() && cont_bg_.empty() && idle_streak_ > 0)
        eff_slice_bytes <<= std::min(idle_streak_, 5);
    const size_t budget_blocks = std::max<size_t>(1, eff_slice_bytes / bs);

    trace_slice(c);  // one tick per PutFrom/GetInto budget slice
    if (ct.op == kOpPutFrom) {
        if (ct.phase == Conn::SegCont::Phase::kAlloc) {
            size_t chunk = std::min(budget_blocks, n - ct.idx);
            // Re-put fast path (kvstore.h overwrite_slot): keys whose
            // current block can be overwritten in place get a nullptr
            // placeholder instead of a fresh block — the copy phase writes
            // straight into the resident block, skipping the per-key
            // lease + make_shared here and the commit + old-block free
            // there. A fresh put (no eligible keys) allocates exactly as
            // before, so the OOM-before-any-commit guarantee is unchanged
            // on that path.
            // Whole-op probe on the first slice: a fully-eligible batch
            // (the steady-state re-put) needs NO allocation at all, and the
            // probe is ~30ns/key — skip straight to the copy phase in one
            // slice instead of sweeping budget_blocks keys per tick.
            if (ct.idx == 0) {
                size_t elig = 0;
                for (size_t i = 0; i < n; i++)
                    if (kv_->overwrite_eligible(ct.m.keys[i], bs)) elig++;
                if (elig == n) {
                    ct.blocks.assign(n, nullptr);
                    ct.idx = n;
                    ct.phase = Conn::SegCont::Phase::kCopy;
                    return;
                }
            }
            size_t need = 0;
            for (size_t i = 0; i < chunk; i++)
                if (!kv_->overwrite_eligible(ct.m.keys[ct.idx + i], bs))
                    need++;
            std::vector<Lease> leases;
            // Budgeted reclaim: a capped demote pass retries next slice
            // instead of 507ing an op the spill tier could still absorb.
            bool ok = true;
            if (need != 0) {
                SliceBudget budget(this, budget_blocks);
                ok = alloc_blocks(bs, need, &leases);
            }
            if (!ok) {
                if (!slice_capped_) finish_cont(c, kStatusOutOfMemory);
                return;  // capped: demotes happened, retry next tick
            }
            size_t li = 0;
            for (size_t i = 0; i < chunk; i++) {
                if (kv_->overwrite_eligible(ct.m.keys[ct.idx + i], bs)) {
                    ct.blocks.push_back(nullptr);
                } else {
                    const Lease& l = leases[li++];
                    ct.blocks.push_back(
                        std::make_shared<Block>(mm_.get(), l.ptr, l.size));
                }
            }
            // Over-allocation corner: a key's eligibility appearing
            // BETWEEN the two sweeps (impossible single-threaded — both
            // run in this slice) would strand a lease; li==need by
            // construction, every lease is owned by a Block above.
            ct.idx += chunk;
            if (ct.idx == n) ct.phase = Conn::SegCont::Phase::kCopy;
            return;
        }
        size_t end = std::min(ct.copied + budget_blocks, n);
        while (ct.copied < end) {
            size_t k = ct.copied;
            if (ct.blocks[k] != nullptr) {
                stream_copy(ct.blocks[k]->data(), seg.base + ct.m.offsets[k],
                            bs);
                kv_->commit(ct.m.keys[k], std::move(ct.blocks[k]));
                ct.copied++;
                continue;
            }
            // Overwrite placeholder from the alloc phase: re-verify NOW —
            // eligibility can lapse between slices (eviction demoted the
            // block, or a GET pinned it).
            BlockRef dst = kv_->overwrite_slot(ct.m.keys[k], bs);
            if (dst != nullptr) {
                stream_copy(dst->data(), seg.base + ct.m.offsets[k], bs);
                ct.copied++;  // entry already committed by identity
                continue;
            }
            // Lapsed: emergency single-block alloc + legacy commit. The
            // only path where OOM can land mid-op (some keys already
            // committed) — it needs eviction or a concurrent pin to race
            // this op between slices AND reclaim to run dry.
            std::vector<Lease> leases;
            bool ok;
            {
                SliceBudget budget(this, budget_blocks);
                ok = alloc_blocks(bs, 1, &leases);
            }
            if (!ok) {
                stream_copy_fence();
                if (!slice_capped_) finish_cont(c, kStatusOutOfMemory);
                return;  // capped: demotes happened, resume here next tick
            }
            BlockRef nb =
                std::make_shared<Block>(mm_.get(), leases[0].ptr, leases[0].size);
            stream_copy(nb->data(), seg.base + ct.m.offsets[k], bs);
            kv_->commit(ct.m.keys[k], std::move(nb));
            ct.copied++;
        }
        // Order the slice's non-temporal stores before anything that
        // publishes them (ring CQE push below, a later GET's socket send).
        stream_copy_fence();
        if (ct.copied == n) {
            if (ct.from_ring) {
                ring_finish(c, kStatusOk, static_cast<uint64_t>(n) * bs);
                return;
            }
            stats_[kOpPutFrom].record(now_us() - c->op_start_us,
                                      static_cast<uint64_t>(n) * bs, 0, true);
            trace_finish(c, static_cast<uint64_t>(n) * bs, true);
            c->cont.reset();
            arm_read(c, true);
            c->reset_read();
            send_resp(c, kStatusOk, {}, {}, {});
        }
        return;
    }

    // kOpGetInto
    if (ct.phase == Conn::SegCont::Phase::kPin) {
        // Shared promotion-work budget (pin_slice); the validator adds the
        // segment bounds check the one-RTT path needs.
        PinResult r = pin_slice(c, [&ct, &seg, bs](size_t k, const BlockRef& b) {
            uint64_t off = ct.m.offsets[k];
            return b->size() <= bs && off <= seg.size && b->size() <= seg.size - off;
        });
        if (r == PinResult::kDone) ct.phase = Conn::SegCont::Phase::kCopy;
        return;
    }
    size_t chunk = std::min(budget_blocks, n - ct.copied);
    for (size_t i = 0; i < chunk; i++) {
        size_t k = ct.copied + i;
        stream_copy(seg.base + ct.m.offsets[k], ct.blocks[k]->data(),
                    ct.blocks[k]->size());
    }
    // The client reads these bytes the moment the completion publishes;
    // drain the write-combining buffers before the CQE / response leaves.
    stream_copy_fence();
    ct.copied += chunk;
    if (ct.copied == n) {
        if (ct.from_ring) {
            uint64_t total = 0;
            for (const auto& b : ct.blocks) total += b->size();
            ring_finish(c, kStatusOk, total);
            return;
        }
        std::vector<uint8_t> body;
        WireWriter w(body);
        w.u32(static_cast<uint32_t>(n));
        uint64_t total = 0;
        for (const auto& b : ct.blocks) {
            w.u32(static_cast<uint32_t>(b->size()));
            total += b->size();
        }
        stats_[kOpGetInto].record(now_us() - c->op_start_us, 0, total, true);
        trace_finish(c, total, true);
        c->cont.reset();
        arm_read(c, true);
        c->reset_read();
        send_resp(c, kStatusOk, std::move(body), {}, {});
    }
}

void Server::arm(Conn* c, bool want_write) {
    if (c->epollout_armed == want_write) return;
    epoll_event ev{};
    ev.events = (c->epollin_armed ? EPOLLIN : 0u) | (want_write ? EPOLLOUT : 0u);
    ev.data.fd = c->fd;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c->fd, &ev);
    c->epollout_armed = want_write;
}

void Server::arm_read(Conn* c, bool want_read) {
    if (c->epollin_armed == want_read) return;
    epoll_event ev{};
    ev.events = (want_read ? EPOLLIN : 0u) | (c->epollout_armed ? EPOLLOUT : 0u);
    ev.data.fd = c->fd;
    epoll_ctl(epoll_fd_, EPOLL_CTL_MOD, c->fd, &ev);
    c->epollin_armed = want_read;
}

void Server::conn_readable(Conn* c) {
    while (true) {
        switch (c->rstate) {
            case Conn::RState::kHeader: {
                ssize_t r = read(c->fd, reinterpret_cast<char*>(&c->hdr) + c->hdr_got,
                                 sizeof(ReqHeader) - c->hdr_got);
                if (r == 0) {
                    close_conn(c);
                    return;
                }
                if (r < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                    close_conn(c);
                    return;
                }
                c->hdr_got += static_cast<size_t>(r);
                if (c->hdr_got < sizeof(ReqHeader)) break;
                // Bad magic / oversized body closes the connection, as in the
                // reference (/root/reference/src/infinistore.cpp:910-915).
                if (c->hdr.magic != kMagic || c->hdr.body_size > kMaxBodySize) {
                    ITS_LOG_WARN("bad header from fd=%d, closing", c->fd);
                    close_conn(c);
                    return;
                }
                c->cur_op = c->hdr.op;
                c->op_start_us = now_us();
                c->body.resize(c->hdr.body_size);
                c->body_got = 0;
                c->rstate = Conn::RState::kBody;
                if (c->hdr.body_size == 0) {
                    dispatch(c);
                    if (c->dead) return;
                }
                break;
            }
            case Conn::RState::kBody: {
                ssize_t r =
                    read(c->fd, c->body.data() + c->body_got, c->body.size() - c->body_got);
                if (r == 0) {
                    close_conn(c);
                    return;
                }
                if (r < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                    close_conn(c);
                    return;
                }
                c->body_got += static_cast<size_t>(r);
                if (c->body_got == c->body.size()) {
                    dispatch(c);
                    if (c->dead) return;
                }
                break;
            }
            case Conn::RState::kPayload: {
                iovec iov[64];
                size_t niov = c->rx_cur.fill(c->rx_iov, iov, 64);
                ssize_t r = readv(c->fd, iov, static_cast<int>(niov));
                if (r == 0) {
                    close_conn(c);
                    return;
                }
                if (r < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                    close_conn(c);
                    return;
                }
                c->rx_cur.advance(c->rx_iov, static_cast<size_t>(r));
                trace_slice(c);  // one tick per readv of a traced put payload
                if (c->rx_cur.done(c->rx_iov)) {
                    finish_payload(c);
                    if (c->dead) return;
                }
                break;
            }
            case Conn::RState::kDrain: {
                // OOM path: the client already streamed its payload; consume
                // and discard it so the connection stays usable, then report.
                char scratch[64 << 10];
                size_t want = std::min(c->drain_remaining, sizeof(scratch));
                ssize_t r = read(c->fd, scratch, want);
                if (r == 0) {
                    close_conn(c);
                    return;
                }
                if (r < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
                    close_conn(c);
                    return;
                }
                c->drain_remaining -= static_cast<size_t>(r);
                if (c->drain_remaining == 0) {
                    uint32_t status = c->drain_status;
                    c->reset_read();
                    send_status(c, status);
                    if (c->dead) return;
                }
                break;
            }
            case Conn::RState::kSuspended:
                // Sliced segment op in progress: EPOLLIN is disarmed, but a
                // level-triggered event already in this tick's batch can
                // still land here — the next request waits in the kernel
                // buffer until the op completes and reads re-arm.
                return;
        }
    }
}

void Server::dispatch(Conn* c) {
    try {
        switch (c->hdr.op) {
            case kOpPutBatch:
                handle_put_batch(c);
                break;
            case kOpGetBatch:
                handle_get_batch(c);
                break;
            case kOpTcpPut:
                handle_tcp_put(c);
                break;
            case kOpShmHello:
            case kOpPutAlloc:
            case kOpPutCommit:
            case kOpGetLoc:
            case kOpRelease:
            case kOpRegSegment:
            case kOpPutFrom:
            case kOpGetInto:
                handle_shm(c);
                break;
            case kOpRingAttach:
                handle_ring_attach(c);
                break;
            case kOpRingDoorbell:
                // Submission-ring doorbell: no body, no response. The wake
                // itself is the payload — drain_rings() runs right after
                // this pass's events.
                ring_counters_.doorbells_rx++;
                c->reset_read();
                break;
            case kOpTcpGet:
            case kOpCheckExist:
            case kOpMatchLastIdx:
            case kOpDeleteKeys:
            case kOpStat:
                handle_simple(c);
                break;
            default:
                ITS_LOG_WARN("unknown op %c from fd=%d, closing", c->hdr.op, c->fd);
                close_conn(c);
                return;
        }
    } catch (const std::exception& e) {
        ITS_LOG_WARN("malformed %c request (%s), closing fd=%d", c->hdr.op, e.what(), c->fd);
        close_conn(c);
    }
}

bool Server::ensure_capacity(size_t need_bytes) {
    (void)need_bytes;
    // Proactive auto-extend above BLOCK_USAGE_RATIO, as the reference's MM
    // signals (/root/reference/src/infinistore.cpp:445, mempool.h:68-78).
    if (config_.auto_increase && mm_->need_extend()) {
        return mm_->extend(config_.extend_pool_bytes);
    }
    return true;
}

bool Server::alloc_blocks(size_t size, size_t n, std::vector<Lease>* leases) {
    slice_capped_ = false;
    // Sliced callers skip the ratio sweep: it can demote min->max ratio of
    // the whole pool in one go (unbounded memcpy work on the reactor); the
    // targeted reclaim below plus the periodic evict task cover them.
    if (!slice_mode_) kv_->evict(config_.evict_min_ratio, config_.evict_max_ratio);
    ensure_capacity(size * n);
    bool ok = mm_->allocate(size, n, nullptr, leases);
    if (!ok && config_.auto_increase && mm_->extend(config_.extend_pool_bytes)) {
        ok = mm_->allocate(size, n, nullptr, leases);
    }
    if (!ok) {
        // A batch larger than the ratio slack: reclaim exactly what it
        // needs (demote with a spill tier, drop without) rather than 507
        // with reclaimable entries present. In-flight refs may keep some
        // freed entries' RAM pinned, so re-try as long as progress is
        // possible; evict_one() draining lru_ bounds the loop. Sliced
        // callers additionally cap the demote iterations per slice and see
        // slice_capped_ (= retry next tick, not OOM).
        size_t bs = mm_->block_size();
        size_t need = ((size + bs - 1) / bs) * bs * n;  // leases are block-granular
        while (mm_->total_bytes() - mm_->used_bytes() < need) {
            if (slice_mode_ && slice_reclaim_left_ == 0) {
                slice_capped_ = true;
                return false;
            }
            if (!kv_->evict_one()) break;
            if (slice_mode_ && slice_reclaim_left_ > 0) slice_reclaim_left_--;
        }
        ok = mm_->allocate(size, n, nullptr, leases);
    }
    return ok;
}

void Server::handle_put_batch(Conn* c) {
    BatchMeta m = BatchMeta::decode(c->body.data(), c->body.size());
    size_t n = m.keys.size();
    // Trace begins at decode so even an op failing validation/404/507
    // closes its server tick (send_status finishes it as not-ok).
    trace_begin(c, m.trace_id, m.trace_parent, m.priority);
    if (n == 0 || m.block_size == 0) {
        c->reset_read();
        send_status(c, kStatusInvalidReq);
        return;
    }
    note_op(m.priority);
    uint64_t need = static_cast<uint64_t>(n) * m.block_size;
    std::vector<Lease> leases;
    if (!alloc_blocks(m.block_size, n, &leases)) {
        // Client streams payload back-to-back with the metadata (no extra
        // RTT), so on OOM we must drain it before answering 507.
        c->body.clear();
        c->rstate = Conn::RState::kDrain;
        c->drain_remaining = need;
        c->drain_status = kStatusOutOfMemory;
        return;
    }
    c->pending_keys = std::move(m.keys);
    c->pending_blocks.reserve(n);
    c->rx_iov.reserve(n);
    for (const auto& lease : leases) {
        c->pending_blocks.push_back(std::make_shared<Block>(mm_.get(), lease.ptr, lease.size));
        c->rx_iov.push_back(iovec{lease.ptr, m.block_size});
    }
    c->rstate = Conn::RState::kPayload;
    c->rx_cur.reset();
}

void Server::handle_tcp_put(Conn* c) {
    TcpPutMeta m = TcpPutMeta::decode(c->body.data(), c->body.size());
    if (m.value_length == 0) {
        c->reset_read();
        send_status(c, kStatusInvalidReq);
        return;
    }
    std::vector<Lease> leases;
    if (!alloc_blocks(m.value_length, 1, &leases)) {
        c->body.clear();
        c->rstate = Conn::RState::kDrain;
        c->drain_remaining = m.value_length;
        c->drain_status = kStatusOutOfMemory;
        return;
    }
    c->pending_keys = {std::move(m.key)};
    c->pending_blocks = {std::make_shared<Block>(mm_.get(), leases[0].ptr, leases[0].size)};
    c->rx_iov = {iovec{leases[0].ptr, m.value_length}};
    c->rstate = Conn::RState::kPayload;
    c->rx_cur.reset();
}

// Shm fast-path control ops: allocate/commit for writes, locate/release for
// reads. Payload never touches the socket — the same-host client memcpys
// straight into/out of the shm-mapped pools (zero-copy in the same sense as
// the reference's one-sided RDMA: one data movement, placed by the server).
void Server::send_loc_resp(Conn* c, ShmLocResp& resp,
                           const std::vector<PoolDirEntry>& dir) {
    // Shared tail of the loc-bearing shm responses: embed the mappable-pool
    // directory and send.
    for (const auto& e : dir)
        resp.pools.push_back(ShmPool{e.pool_id, e.shm_name, e.size});
    std::vector<uint8_t> body;
    resp.encode(body);
    c->reset_read();
    send_resp(c, kStatusOk, std::move(body), {}, {});
}

bool Server::shm_mappable(const void* ptr, const std::vector<PoolDirEntry>& dir,
                          PoolLoc* out) {
    // A location is only usable if its pool is in the shm directory; a pool
    // that fell back to anonymous memory (e.g. /dev/shm quota hit during
    // auto-extend) is reachable only via the socket path.
    *out = mm_->locate(ptr);
    if (!out->found) return false;
    for (const auto& e : dir)
        if (e.pool_id == out->pool_id) return true;
    return false;
}

void Server::handle_shm(Conn* c) {
    switch (c->hdr.op) {
        case kOpShmHello: {
            ShmLocResp resp;
            send_loc_resp(c, resp, mm_->pool_dir());
            return;
        }
        case kOpPutAlloc: {
            BatchMeta m = BatchMeta::decode(c->body.data(), c->body.size());
            trace_begin(c, m.trace_id, m.trace_parent, m.priority);
            size_t n = m.keys.size();
            if (n == 0 || m.block_size == 0 || !mm_->shm_enabled()) {
                c->reset_read();
                send_status(c, kStatusInvalidReq);
                return;
            }
            // Allocation runs budget-sliced (run_putalloc_slice): leases
            // already obtained are BANKED in the continuation as BlockRefs,
            // so progress is monotone even with other connections
            // allocating concurrently — the op completes, or reclaim runs
            // genuinely dry (507). The no-pressure case completes in its
            // first slice, same reactor tick as this dispatch.
            note_op(m.priority);
            auto cont = std::make_unique<Conn::SegCont>();
            cont->op = kOpPutAlloc;
            cont->prio = m.priority;
            cont->m.keys = std::move(m.keys);
            cont->m.block_size = m.block_size;
            cont->blocks.reserve(n);
            c->cont = std::move(cont);
            // First slice inline: the free-RAM case completes right here
            // with no suspension (no epoll re-arms, no extra tick) — unless
            // the op is BACKGROUND class and foreground work is live, in
            // which case it queues for the two-level scheduler instead of
            // jumping it.
            if (m.priority == kPriorityBackground && bg_must_defer()) {
                suspend_for_cont(c);
                return;
            }
            run_putalloc_slice(c);
            if (!c->dead && c->cont != nullptr) suspend_for_cont(c);
            return;
        }
        case kOpPutCommit: {
            TicketMeta m = TicketMeta::decode(c->body.data(), c->body.size());
            auto it = c->pending_puts.find(m.ticket);
            if (it == c->pending_puts.end()) {
                c->reset_read();
                send_status(c, kStatusInvalidReq);
                return;
            }
            uint64_t in_bytes = 0;
            auto& pending = it->second;
            uint64_t op_start = pending.start_us ? pending.start_us : c->op_start_us;
            for (size_t i = 0; i < pending.keys.size(); i++) {
                in_bytes += pending.blocks[i]->size();
                kv_->commit(pending.keys[i], std::move(pending.blocks[i]));
            }
            c->pending_puts.erase(it);
            // Account under 'p' so /stats distinguishes which plane writes
            // rode ('W' socket, 'p' shm two-phase, 'F' one-RTT segment).
            // Latency spans alloc -> commit (see PendingPut::start_us).
            stats_[kOpPutAlloc].record(now_us() - op_start, in_bytes, 0, true);
            c->reset_read();
            send_resp(c, kStatusOk, {}, {}, {});
            return;
        }
        case kOpGetLoc: {
            BatchMeta m = BatchMeta::decode(c->body.data(), c->body.size());
            trace_begin(c, m.trace_id, m.trace_parent, m.priority);
            if (m.keys.empty() || m.block_size == 0 || !mm_->shm_enabled()) {
                c->reset_read();
                send_status(c, kStatusInvalidReq);
                return;
            }
            for (const auto& key : m.keys) {
                if (!kv_->exists(key)) {
                    c->reset_read();
                    send_status(c, kStatusKeyNotFound);
                    return;
                }
            }
            // Promotion (pin) work runs budget-sliced (run_cont_slice):
            // pins persist in the continuation, so progress is monotone —
            // the op either completes or genuinely exhausts reclaim (507).
            note_op(m.priority);
            auto cont = std::make_unique<Conn::SegCont>();
            cont->op = kOpGetLoc;
            cont->prio = m.priority;
            cont->m.keys = std::move(m.keys);
            cont->m.block_size = m.block_size;
            cont->phase = Conn::SegCont::Phase::kPin;
            cont->blocks.reserve(cont->m.keys.size());
            c->cont = std::move(cont);
            // First slice inline: a RAM-resident batch completes right here
            // with no suspension (no epoll re-arms, no extra tick) — same
            // BACKGROUND deferral as PutAlloc above.
            if (m.priority == kPriorityBackground && bg_must_defer()) {
                suspend_for_cont(c);
                return;
            }
            run_getloc_slice(c);
            if (!c->dead && c->cont != nullptr) suspend_for_cont(c);
            return;
        }
        case kOpRelease: {
            TicketMeta m = TicketMeta::decode(c->body.data(), c->body.size());
            c->pending_gets.erase(m.ticket);
            c->pending_puts.erase(m.ticket);  // abort path for unmappable pools
            c->reset_read();  // fire-and-forget: no response
            return;
        }
        case kOpRegSegment: {
            SegMeta m = SegMeta::decode(c->body.data(), c->body.size());
            uint32_t status = kStatusInvalidReq;
            // Only map segments this library created (its. prefix), and only
            // when tmpfs really backs the declared size — a shorter segment
            // would SIGBUS the server on the first memcpy past EOF.
            if (mm_->shm_enabled() && m.size > 0 &&
                m.name.rfind("/its.", 0) == 0 &&
                c->segments.find(m.seg_id) == c->segments.end()) {
                int fd = shm_open(m.name.c_str(), O_RDWR, 0);
                if (fd >= 0) {
                    struct stat st;
                    if (fstat(fd, &st) == 0 &&
                        st.st_size >= static_cast<off_t>(m.size)) {
                        void* mem = mmap(nullptr, m.size, PROT_READ | PROT_WRITE,
                                         MAP_SHARED, fd, 0);
                        if (mem != MAP_FAILED) {
                            c->segments[m.seg_id] =
                                Conn::SegMap{static_cast<char*>(mem), m.size};
                            status = kStatusOk;
                        }
                    }
                    ::close(fd);
                }
            }
            c->reset_read();
            send_status(c, status);
            return;
        }
        case kOpPutFrom: {
            // Pull blocks out of the client segment, commit, single ack —
            // the reference's write path shape (server-initiated RDMA READ,
            // reference src/infinistore.cpp:558-595) on shm. Validation runs
            // here; the alloc/demote and memcpy work runs budget-sliced
            // across loop ticks (run_cont_slice) so other connections are
            // served in between.
            SegBatchMeta m = SegBatchMeta::decode(c->body.data(), c->body.size());
            trace_begin(c, m.trace_id, m.trace_parent, m.priority);
            size_t n = m.keys.size();
            auto seg_it = c->segments.find(m.seg_id);
            if (n == 0 || m.block_size == 0 || n != m.offsets.size() ||
                seg_it == c->segments.end()) {
                c->reset_read();
                send_status(c, kStatusInvalidReq);
                return;
            }
            const Conn::SegMap& seg = seg_it->second;
            for (uint64_t off : m.offsets) {
                if (off > seg.size || m.block_size > seg.size - off) {
                    c->reset_read();
                    send_status(c, kStatusInvalidReq);
                    return;
                }
            }
            note_op(m.priority);
            auto cont = std::make_unique<Conn::SegCont>();
            cont->op = kOpPutFrom;
            cont->prio = m.priority;
            cont->m = std::move(m);
            cont->blocks.reserve(n);
            c->cont = std::move(cont);
            suspend_for_cont(c);
            return;
        }
        case kOpGetInto: {
            // Push stored blocks into the client segment (RDMA WRITE
            // analogue, reference :600-637); resp body carries stored sizes.
            // Existence is checked up front; promotion (pin) and the
            // memcpys run budget-sliced, all-or-nothing before the first
            // segment write (pin phase completes before any copy).
            SegBatchMeta m = SegBatchMeta::decode(c->body.data(), c->body.size());
            trace_begin(c, m.trace_id, m.trace_parent, m.priority);
            if (m.keys.empty() || m.block_size == 0 || m.keys.size() != m.offsets.size() ||
                c->segments.find(m.seg_id) == c->segments.end()) {
                c->reset_read();
                send_status(c, kStatusInvalidReq);
                return;
            }
            for (const auto& key : m.keys) {
                if (!kv_->exists(key)) {
                    c->reset_read();
                    send_status(c, kStatusKeyNotFound);
                    return;
                }
            }
            note_op(m.priority);
            auto cont = std::make_unique<Conn::SegCont>();
            cont->op = kOpGetInto;
            cont->prio = m.priority;
            cont->m = std::move(m);
            cont->phase = Conn::SegCont::Phase::kPin;
            cont->blocks.reserve(cont->m.keys.size());
            c->cont = std::move(cont);
            suspend_for_cont(c);
            return;
        }
        default:
            c->reset_read();
            send_status(c, kStatusInvalidReq);
    }
}

// Map + validate a client-created descriptor ring. Geometry comes from the
// mapped RingCtrl itself (ring_view_init checks magic/version/struct-size
// echoes/bounds); the attach body only names the segment. Same trust rules
// as RegSegment: our own "/its." namespace, tmpfs really backing the
// declared size.
void Server::handle_ring_attach(Conn* c) {
    RingMeta m = RingMeta::decode(c->body.data(), c->body.size());
    uint32_t status = kStatusInvalidReq;
    if (mm_->shm_enabled() && c->ring == nullptr && m.size >= kRingCtrlSpan &&
        m.name.rfind("/its.", 0) == 0) {
        int fd = shm_open(m.name.c_str(), O_RDWR, 0);
        if (fd >= 0) {
            struct stat st;
            if (fstat(fd, &st) == 0 && st.st_size >= static_cast<off_t>(m.size)) {
                void* mem =
                    mmap(nullptr, m.size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
                if (mem != MAP_FAILED) {
                    auto ring = std::make_unique<Conn::RingSrv>();
                    if (ring_view_init(&ring->view, static_cast<char*>(mem), m.size)) {
                        ring->sq_seq = ring_load_acq(&ring->view.ctrl->sq_tail);
                        ring->cq_seq = ring_load_acq(&ring->view.ctrl->cq_tail);
                        c->ring = std::move(ring);
                        ring_conns_.push_back(c);
                        ring_counters_.attached++;
                        status = kStatusOk;
                    } else {
                        munmap(mem, m.size);
                    }
                }
            }
            ::close(fd);
        }
    }
    c->reset_read();
    send_status(c, status);
}

void Server::finish_payload(Conn* c) {
    // Commit-on-transfer-complete: keys become visible only now (reference
    // commits on RDMA READ completion, /root/reference/src/infinistore.cpp:405-418).
    uint64_t in_bytes = 0;
    for (size_t i = 0; i < c->pending_keys.size(); i++) {
        in_bytes += c->pending_blocks[i]->size();
        kv_->commit(c->pending_keys[i], std::move(c->pending_blocks[i]));
    }
    uint8_t op = c->cur_op;
    uint64_t us = now_us() - c->op_start_us;
    stats_[op].record(us, in_bytes, 0, true);
    trace_finish(c, in_bytes, true);
    c->reset_read();
    send_resp(c, kStatusOk, {}, {}, {});
}

void Server::handle_get_batch(Conn* c) {
    BatchMeta m = BatchMeta::decode(c->body.data(), c->body.size());
    trace_begin(c, m.trace_id, m.trace_parent, m.priority);
    if (m.keys.empty() || m.block_size == 0) {
        c->reset_read();
        send_status(c, kStatusInvalidReq);
        return;
    }
    note_op(m.priority);
    // All keys must exist (reference read_rdma_cache,
    // /root/reference/src/infinistore.cpp:612-617)...
    for (const auto& key : m.keys) {
        if (!kv_->exists(key)) {
            c->reset_read();
            send_status(c, kStatusKeyNotFound);
            return;
        }
    }
    std::vector<BlockRef> refs;
    std::vector<iovec> payload;
    std::vector<uint8_t> body;
    WireWriter w(body);
    w.u32(static_cast<uint32_t>(m.keys.size()));
    uint64_t total = 0;
    for (const auto& key : m.keys) {
        BlockRef b = kv_->get(key);  // touches LRU (reference :629-634)
        if (b == nullptr) {  // spilled + unpromotable: cold but alive — 512
            c->reset_read();
            send_status(c, kStatusColdTier);
            return;
        }
        // ...and each stored size must fit the client's block stride (:620-624).
        if (b->size() > m.block_size) {
            c->reset_read();
            send_status(c, kStatusInvalidReq);
            return;
        }
        w.u32(static_cast<uint32_t>(b->size()));
        payload.push_back(iovec{b->data(), b->size()});
        total += b->size();
        refs.push_back(std::move(b));
    }
    uint8_t op = c->cur_op;
    uint64_t us = now_us() - c->op_start_us;
    stats_[op].record(us, 0, total, true);
    // The whole gather assembled in one pass: first and last slice coincide.
    trace_slice(c);
    trace_finish(c, total, true);
    c->reset_read();
    send_resp(c, kStatusOk, std::move(body), std::move(payload), std::move(refs));
}

void Server::handle_simple(Conn* c) {
    std::vector<uint8_t> body;
    std::vector<iovec> payload;
    std::vector<BlockRef> refs;
    uint32_t status = kStatusOk;
    WireWriter w(body);

    switch (c->hdr.op) {
        case kOpTcpGet: {
            KeyMeta m = KeyMeta::decode(c->body.data(), c->body.size());
            bool present = kv_->exists(m.key);
            BlockRef b = kv_->get(m.key);
            if (b == nullptr) {
                // Present-but-unpromotable (spill tier, RAM pressure) is
                // the typed 512 "cold but alive" — the data survives one
                // tier down; only a truly absent key is 404, and 507 stays
                // reserved for genuine allocation exhaustion.
                status = present ? kStatusColdTier : kStatusKeyNotFound;
            } else {
                payload.push_back(iovec{b->data(), b->size()});
                refs.push_back(std::move(b));
            }
            break;
        }
        case kOpCheckExist: {
            KeyMeta m = KeyMeta::decode(c->body.data(), c->body.size());
            w.u8(kv_->exists(m.key) ? 1 : 0);
            break;
        }
        case kOpMatchLastIdx: {
            KeyListMeta m = KeyListMeta::decode(c->body.data(), c->body.size());
            w.i32(kv_->match_last_index(m.keys));
            break;
        }
        case kOpDeleteKeys: {
            KeyListMeta m = KeyListMeta::decode(c->body.data(), c->body.size());
            w.u32(static_cast<uint32_t>(kv_->remove(m.keys)));
            break;
        }
        case kOpStat: {
            // stats_json() runs inline: we are on the reactor thread.
            std::string s = stats_json();
            body.assign(s.begin(), s.end());
            break;
        }
        default:
            status = kStatusInvalidReq;
    }
    uint64_t out_bytes = 0;
    for (const auto& io : payload) out_bytes += io.iov_len;
    uint8_t op = c->cur_op;
    uint64_t us = now_us() - c->op_start_us;
    stats_[op].record(us, 0, out_bytes, status == kStatusOk);
    c->reset_read();
    send_resp(c, status, std::move(body), std::move(payload), std::move(refs));
}

void Server::send_status(Conn* c, uint32_t status) {
    if (status != kStatusOk) stats_[c->cur_op].record(now_us() - c->op_start_us, 0, 0, false);
    // A traced op erroring out (404/507/400, finish_cont, drain) still
    // closes its server tick — the client span's error status gets its
    // server-side timeline either way.
    trace_finish(c, 0, status == kStatusOk);
    send_resp(c, status, {}, {}, {});
}

void Server::send_resp(Conn* c, uint32_t status, std::vector<uint8_t> body,
                       std::vector<iovec> payload, std::vector<BlockRef> refs) {
    Conn::OutMsg msg;
    msg.hdr.status = status;
    msg.hdr.body_size = static_cast<uint32_t>(body.size());
    uint64_t ptotal = 0;
    for (const auto& io : payload) ptotal += io.iov_len;
    msg.hdr.payload_size = ptotal;
    msg.body = std::move(body);
    msg.payload = std::move(payload);
    msg.refs = std::move(refs);
    msg.total = sizeof(RespHeader) + msg.body.size() + ptotal;
    c->outq.push_back(std::move(msg));
    flush_out(c);
}

void Server::flush_out(Conn* c) {
    while (!c->outq.empty()) {
        Conn::OutMsg& msg = c->outq.front();
        iovec iov[64];
        size_t niov =
            build_send_iov(&msg.hdr, sizeof(RespHeader), msg.body, msg.payload, msg.sent, iov, 64);
        if (niov == 0) {
            c->outq.pop_front();
            continue;
        }
        ssize_t r = writev_nosignal(c->fd, iov, static_cast<int>(niov));
        if (r < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                arm(c, true);
                return;
            }
            close_conn(c);
            return;
        }
        msg.sent += static_cast<size_t>(r);
        if (msg.sent == msg.total) c->outq.pop_front();
    }
    arm(c, false);
}

void Server::conn_writable(Conn* c) { flush_out(c); }

}  // namespace its
