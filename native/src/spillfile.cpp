#include "its/spillfile.h"

#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

#include <atomic>

#include "its/log.h"

namespace its {

static std::atomic<uint32_t> g_spill_seq{0};

SpillFile::SpillFile(const std::string& dir, size_t bytes, size_t block_size)
    : block_size_(block_size) {
    size_t nblocks = bytes / block_size;
    if (nblocks == 0) {
        ITS_LOG_ERROR("spill: %zu bytes < one %zu-byte block; tier disabled", bytes,
                      block_size);
        return;
    }
    std::string path = dir + "/its-spill-" + std::to_string(getpid()) + "-" +
                       std::to_string(g_spill_seq.fetch_add(1)) + ".dat";
    int fd = open(path.c_str(), O_RDWR | O_CREAT | O_EXCL | O_CLOEXEC, 0600);
    if (fd < 0) {
        ITS_LOG_ERROR("spill: cannot create %s: %s; tier disabled", path.c_str(),
                      strerror(errno));
        return;
    }
    // Unlink NOW: the mapping keeps the inode alive, and any exit — clean,
    // crash, or SIGKILL — reclaims the space with no sweeper.
    unlink(path.c_str());
    size_t total = nblocks * block_size;
    if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
        ITS_LOG_ERROR("spill: ftruncate(%zu) failed: %s; tier disabled", total,
                      strerror(errno));
        close(fd);
        return;
    }
    void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);  // the mapping holds its own reference
    if (mem == MAP_FAILED) {
        ITS_LOG_ERROR("spill: mmap(%zu) failed: %s; tier disabled", total,
                      strerror(errno));
        return;
    }
    base_ = static_cast<char*>(mem);
    alloc_.init(nblocks);
    ITS_LOG_INFO("spill tier: %zu MB at %s (unlinked), block %zu KB", total >> 20,
                 path.c_str(), block_size >> 10);
}

SpillFile::~SpillFile() {
    if (base_ != nullptr) munmap(base_, alloc_.total * block_size_);
}

int64_t SpillFile::alloc(size_t size) {
    if (base_ == nullptr || size == 0) return -1;
    size_t nblocks = (size + block_size_ - 1) / block_size_;
    size_t first = alloc_.alloc_run(nblocks);
    if (first == SIZE_MAX) return -1;
    return static_cast<int64_t>(first * block_size_);
}

void SpillFile::free_slot(int64_t offset, size_t size) {
    if (base_ == nullptr || offset < 0) return;
    alloc_.free_run(static_cast<size_t>(offset) / block_size_,
                    (size + block_size_ - 1) / block_size_);
}

}  // namespace its
