// File-backed spill tier for evicted KV blocks.
//
// The reference aspires to an SSD tier but never built one (reference
// docs/source/design.rst:36 "SSD" is listed as a future pool; kv_map is
// in-RAM only). This is that tier: a single mmap'd file carved into
// block-granular slots by the same first-fit bitmap discipline as the RAM
// pools (mempool.h). Eviction memcpys a block's bytes into a slot instead of
// dropping them; a later get() promotes the bytes back into a RAM pool. All
// I/O rides the page cache (mmap MAP_SHARED), so spills are memcpy-speed and
// the kernel writes back lazily; the file is unlinked at open, so any crash
// (including SIGKILL) reclaims the space with zero cleanup code.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "its/bitmap_alloc.h"

namespace its {

class SpillFile {
  public:
    // Creates <dir>/its-spill-<pid>-<seq>.dat of `bytes` (rounded down to a
    // block multiple), mmaps it, and unlinks it immediately. ok() is false
    // (and the tier disabled) when the directory is unwritable or the
    // mapping fails.
    SpillFile(const std::string& dir, size_t bytes, size_t block_size);
    ~SpillFile();
    SpillFile(const SpillFile&) = delete;
    SpillFile& operator=(const SpillFile&) = delete;

    bool ok() const { return base_ != nullptr; }

    // Allocate ceil(size/block_size) contiguous blocks; returns the byte
    // offset, or -1 when no run is free.
    int64_t alloc(size_t size);
    void free_slot(int64_t offset, size_t size);

    char* data(int64_t offset) const { return base_ + offset; }
    size_t total_bytes() const { return alloc_.total * block_size_; }
    size_t used_bytes() const { return alloc_.used * block_size_; }

  private:
    char* base_ = nullptr;
    size_t block_size_ = 0;
    BitmapAlloc alloc_;
};

}  // namespace its
