// Descriptor-ring shared-memory view (docs/descriptor_ring.md).
//
// The ring segment is created by the client, mapped by both processes, and
// laid out deterministically from the geometry in its RingCtrl:
//
//   [0, kRingCtrlSpan)                    RingCtrl (page-sized span)
//   [sq_off, sq_off + sq_slots * 24)      RingSlot submission array
//   [cq_off, cq_off + cq_slots * 32)      RingCqe completion array
//   [meta_off, meta_off + sq_slots * meta_stride)   per-SQ-slot meta arena
//
// Cursors are monotonic u64 sequence numbers; slot index = seq % slots.
// Single producer / single consumer per ring direction:
//   SQ: client threads produce (serialized by the connection's ring mutex),
//       the server reactor consumes.
//   CQ: the server reactor produces, the client reactor consumes.
// Publish discipline both directions: write the record, release-store its
// gen = seq + 1, release-store the tail = seq + 1. The consumer
// acquire-loads the tail, then checks gen == seq + 1 — a mismatch under an
// advanced tail means a torn or corrupt descriptor (generation-tag
// validation) and poisons the ring. Record memory is reusable only once the
// consumer has release-stored its head past the sequence.
//
// Doze/wake doorbells: each consumer parks by seq_cst-storing its *_waiting
// flag, then re-checking the tail before blocking in epoll (Dekker pairing
// with the producer's publish + seq_cst flag read). A producer that
// observes the flag set CASes it down and sends exactly one doorbell over
// the socket — kOpRingDoorbell client->server, a kStatusRingEvent response
// frame server->client. While the consumer is awake, posting is pure shared
// memory: zero syscalls per op.
//
// All cross-process field access goes through the __atomic helpers below
// (std::atomic_ref is C++20; these are the C++17 equivalent and TSAN
// understands them).
#pragma once

#include <cstdint>

#include "its/protocol.h"

namespace its {

inline uint64_t ring_align64(uint64_t v) { return (v + 63) & ~uint64_t{63}; }

inline uint64_t ring_sq_off() { return kRingCtrlSpan; }
inline uint64_t ring_cq_off(uint32_t sq_slots) {
    return ring_sq_off() + ring_align64(uint64_t{sq_slots} * sizeof(RingSlot));
}
inline uint64_t ring_meta_off(uint32_t sq_slots, uint32_t cq_slots) {
    return ring_cq_off(sq_slots) + ring_align64(uint64_t{cq_slots} * sizeof(RingCqe));
}
inline uint64_t ring_segment_bytes(uint32_t sq_slots, uint32_t cq_slots,
                                   uint32_t meta_stride) {
    return ring_meta_off(sq_slots, cq_slots) + uint64_t{sq_slots} * meta_stride;
}

// -- cross-process atomics (all fields naturally aligned; see RingCtrl) -----

inline uint64_t ring_load_acq(const uint64_t* p) {
    return __atomic_load_n(p, __ATOMIC_ACQUIRE);
}
inline void ring_store_rel(uint64_t* p, uint64_t v) {
    __atomic_store_n(p, v, __ATOMIC_RELEASE);
}
// Full barrier between a publish (tail store / flag park) and the paired
// re-read on the other variable — the classic lost-wakeup Dekker fence.
inline void ring_fence() { __atomic_thread_fence(__ATOMIC_SEQ_CST); }

inline void ring_flag_park(uint32_t* flag) {
    __atomic_store_n(flag, 1u, __ATOMIC_SEQ_CST);
}
inline void ring_flag_clear(uint32_t* flag) {
    __atomic_store_n(flag, 0u, __ATOMIC_SEQ_CST);
}
// True when the producer should send a doorbell: the consumer was parked
// and this caller won the unpark (exactly one doorbell per doze).
inline bool ring_flag_take(uint32_t* flag) {
    uint32_t expect = 1u;
    return __atomic_load_n(flag, __ATOMIC_SEQ_CST) == 1u &&
           __atomic_compare_exchange_n(flag, &expect, 0u, false, __ATOMIC_SEQ_CST,
                                       __ATOMIC_SEQ_CST);
}

// Mapped view over a ring segment. The geometry is SNAPSHOTTED out of the
// control block at ring_view_init (after validation) and never re-read:
// the ctrl fields live in memory the peer can scribble on, and index
// arithmetic against a live `sq_slots` would hand a hostile writer a
// div-by-zero / out-of-bounds primitive. The shared ctrl is dereferenced
// only for the cursors and doze flags, whose values are never trusted
// beyond bounded comparisons.
struct RingView {
    char* base = nullptr;
    uint64_t size = 0;
    RingCtrl* ctrl = nullptr;
    RingSlot* sq = nullptr;
    RingCqe* cq = nullptr;
    char* meta = nullptr;
    uint32_t sq_slots = 0;     // snapshot (validated power of two)
    uint32_t cq_slots = 0;     // snapshot
    uint32_t meta_stride = 0;  // snapshot

    RingSlot* slot(uint64_t seq) { return &sq[seq % sq_slots]; }
    RingCqe* cqe(uint64_t seq) { return &cq[seq % cq_slots]; }
    char* meta_at(uint64_t seq) {
        return meta + (seq % sq_slots) * uint64_t{meta_stride};
    }
};

// -- adaptive poll-then-park (shared by client reactor and server loop) -----
//
// Before arming its doorbell (parking the *_waiting flag and blocking in
// epoll), a consumer busy-polls its ring for a short budget derived from an
// EWMA of recent inter-arrival gaps: when completions/descriptors are
// landing back-to-back the next one is caught without any syscall or
// doorbell; when the cadence is slow — or the ring idle — the budget is
// zero and the consumer parks immediately, so a quiet connection costs no
// CPU. The poll loop must yield each spin (std::this_thread::yield) so a
// same-core peer can make the progress being polled for.

constexpr uint64_t kRingPollCapUs = 200;      // hard busy-poll bound
constexpr uint64_t kRingPollMinUs = 5;        // floor once polling at all
constexpr uint64_t kRingPollDefaultUs = 50;   // optimistic budget before samples
// Server-side gate: poll only while a descriptor arrived this recently.
constexpr uint64_t kRingPollRecentUs = 1000;

// Poll budget for the observed cadence: ~2x the smoothed gap, clamped to
// [kRingPollMinUs, kRingPollCapUs]; gaps beyond the cap are not worth
// spinning for (park immediately, the doorbell path handles it).
inline uint64_t ring_poll_budget(uint64_t ewma_gap_us) {
    if (ewma_gap_us == 0) return kRingPollDefaultUs;
    if (ewma_gap_us > kRingPollCapUs) return 0;
    uint64_t b = 2 * ewma_gap_us;
    if (b < kRingPollMinUs) return kRingPollMinUs;
    return b < kRingPollCapUs ? b : kRingPollCapUs;
}

// Fold one arrival timestamp into the gap EWMA (alpha = 1/8). Both fields
// are owned by the consuming reactor thread — no atomics needed.
inline void ring_gap_note(uint64_t* ewma_us, uint64_t* last_us, uint64_t now_us) {
    if (*last_us != 0 && now_us >= *last_us) {
        uint64_t gap = now_us - *last_us;
        *ewma_us = (*ewma_us == 0) ? gap : (*ewma_us * 7 + gap) / 8;
    }
    *last_us = now_us;
}

// Build a view over mapped memory, validating the control block against
// this build's struct sizes and the mapped span. Returns false (view
// untouched) on any mismatch — the caller must fall back to the socket
// path rather than trust a layout it does not share.
inline bool ring_view_init(RingView* v, char* base, uint64_t size) {
    if (base == nullptr || size < kRingCtrlSpan) return false;
    RingCtrl* ctrl = reinterpret_cast<RingCtrl*>(base);
    if (ctrl->magic != kRingMagic || ctrl->version != kRingVersion) return false;
    if (ctrl->slot_bytes != sizeof(RingSlot) || ctrl->cqe_bytes != sizeof(RingCqe))
        return false;
    uint32_t sq = ctrl->sq_slots, cq = ctrl->cq_slots, stride = ctrl->meta_stride;
    if (sq == 0 || (sq & (sq - 1)) != 0 || cq == 0 || (cq & (cq - 1)) != 0)
        return false;
    if (stride == 0 || stride > kMaxBodySize) return false;
    if (ring_segment_bytes(sq, cq, stride) > size) return false;
    v->base = base;
    v->size = size;
    v->ctrl = ctrl;
    v->sq = reinterpret_cast<RingSlot*>(base + ring_sq_off());
    v->cq = reinterpret_cast<RingCqe*>(base + ring_cq_off(sq));
    v->meta = base + ring_meta_off(sq, cq);
    v->sq_slots = sq;
    v->cq_slots = cq;
    v->meta_stride = stride;
    return true;
}

}  // namespace its
