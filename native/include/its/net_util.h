// Socket helpers shared by the client and server reactors.
#pragma once

#include <errno.h>
#include <string.h>
#include <sys/socket.h>

#include <cstdint>

#include "its/log.h"

namespace its {

// writev for sockets that cannot raise SIGPIPE: a peer that closes mid-write
// must surface as EPIPE to the reactor, not kill the embedding process
// (Python masks SIGPIPE, so only native embedders ever saw the default
// disposition — found by the native abandoned-op stress test).
inline ssize_t writev_nosignal(int fd, const struct iovec* iov, int niov) {
    msghdr msg{};
    msg.msg_iov = const_cast<struct iovec*>(iov);
    msg.msg_iovlen = static_cast<size_t>(niov);
    return sendmsg(fd, &msg, MSG_NOSIGNAL);
}

// Cap a socket's egress with SO_MAX_PACING_RATE (TCP internal pacing — works
// without an fq qdisc since Linux 4.13). mbps == 0 leaves the socket
// unlimited. The u32 sockopt form caps at 4 GB/s; rates at or above 4096
// MB/s mean "unlimited" here, which is the only sane reading of such a cap.
inline void set_pacing_rate(int fd, uint32_t mbps, const char* who) {
    if (mbps == 0) return;
    uint32_t rate = mbps >= (1u << 12) ? UINT32_MAX : mbps << 20;  // MB/s -> B/s
    if (setsockopt(fd, SOL_SOCKET, SO_MAX_PACING_RATE, &rate, sizeof(rate)) != 0)
        ITS_LOG_WARN("%s: SO_MAX_PACING_RATE(%u MB/s) failed: %s — egress UNCAPPED",
                     who, mbps, strerror(errno));
}

}  // namespace its
