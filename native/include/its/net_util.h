// Socket helpers shared by the client and server reactors.
#pragma once

#include <errno.h>
#include <string.h>
#include <sys/socket.h>

#include <cstdint>

#include "its/log.h"

namespace its {

// Cap a socket's egress with SO_MAX_PACING_RATE (TCP internal pacing — works
// without an fq qdisc since Linux 4.13). mbps == 0 leaves the socket
// unlimited. The u32 sockopt form caps at 4 GB/s; rates at or above 4096
// MB/s mean "unlimited" here, which is the only sane reading of such a cap.
inline void set_pacing_rate(int fd, uint32_t mbps, const char* who) {
    if (mbps == 0) return;
    uint32_t rate = mbps >= (1u << 12) ? UINT32_MAX : mbps << 20;  // MB/s -> B/s
    if (setsockopt(fd, SOL_SOCKET, SO_MAX_PACING_RATE, &rate, sizeof(rate)) != 0)
        ITS_LOG_WARN("%s: SO_MAX_PACING_RATE(%u MB/s) failed: %s — egress UNCAPPED",
                     who, mbps, strerror(errno));
}

}  // namespace its
