// Block-copy primitive for the budget-sliced segment data plane, with an
// opt-in non-temporal (cache-bypassing) path.
//
// The sliced copy engine moves whole 64KB-class blocks between the client
// shm segment and pool blocks. Two regimes matter:
//
//  - Working set LARGER than LLC (DRAM-bound): plain memcpy pays a
//    read-for-ownership on every destination line and evicts working set;
//    non-temporal stores skip both. Measured on the bench host, NT moves
//    same-direction 64KB block streams ~40% faster (write leg 8.1 -> 5.5ms
//    per 64MB).
//  - Working set INSIDE the LLC (the loopback headline: 128MB hot set,
//    260MB L3): plain stores keep the set cache-resident across the
//    alternating write/read legs, and NT is a large NET LOSS — it forces
//    full DRAM round trips on both legs (measured 17.5ms vs 12.3ms per
//    write+read pair).
//
// The second regime is the one the paired ceiling estimator actually runs
// in, so ITS_STREAM_COPY_NT is OFF by default and stream_copy compiles to
// memcpy. Hosts whose transfer working set exceeds the LLC can opt in at
// build time (-DITS_STREAM_COPY_NT=1); the call sites already carry the
// required fences.
//
// Caller contract under NT: non-temporal stores are weakly ordered — they
// are NOT ordered by a later std::atomic release store. Callers must
// execute stream_copy_fence() after a run of stream_copy() calls and
// BEFORE any cross-thread/cross-process publish of the copied bytes (ring
// CQE push, socket write; kv commit visibility to a future pinned reader
// is same-thread and needs no fence, but the fence is cheap enough to sit
// at the end of every copy slice). Loads on the copying thread itself
// always see its own prior stores (x86 program order), so intra-slice
// readback — e.g. commit bookkeeping — is safe without a fence. With NT
// off the fence is a no-op.
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(ITS_STREAM_COPY_NT) && !(defined(__x86_64__) || defined(_M_X64))
#undef ITS_STREAM_COPY_NT  // NT path is x86-only; others fall back to memcpy
#endif
#ifdef ITS_STREAM_COPY_NT
#include <emmintrin.h>
#endif

namespace its {

// Copies below this stay on memcpy: the fixed head/tail handling and the
// WC-buffer drain are not worth it, and sub-page copies likely ARE re-read
// soon (metadata, small values).
constexpr size_t kStreamCopyMinBytes = 4096;

inline void stream_copy(void* dst, const void* src, size_t n) {
#ifdef ITS_STREAM_COPY_NT
    if (n < kStreamCopyMinBytes) {
        memcpy(dst, src, n);
        return;
    }
    char* d = static_cast<char*>(dst);
    const char* s = static_cast<const char*>(src);
    // Align the DESTINATION to the 64B line; movntdq requires 16B alignment
    // and full-line runs keep the write-combining buffers merging.
    size_t head = (64 - (reinterpret_cast<uintptr_t>(d) & 63)) & 63;
    if (head != 0) {
        memcpy(d, s, head);
        d += head;
        s += head;
        n -= head;
    }
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        __m128i a = _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i));
        __m128i b =
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 16));
        __m128i c =
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 32));
        __m128i e =
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(s + i + 48));
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i), a);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 16), b);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 32), c);
        _mm_stream_si128(reinterpret_cast<__m128i*>(d + i + 48), e);
    }
    if (i < n) memcpy(d + i, s + i, n - i);
#else
    memcpy(dst, src, n);
#endif
}

// Drain the write-combining buffers: order all prior stream_copy() stores
// before any subsequent store (CQE publish, doorbell, socket send).
inline void stream_copy_fence() {
#ifdef ITS_STREAM_COPY_NT
    _mm_sfence();
#endif
}

}  // namespace its
