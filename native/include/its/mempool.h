// Pinned host-DRAM memory pool with a first-fit bitmap allocator.
//
// TPU-native analogue of the reference's mempool (/root/reference/src/mempool.h
// :19-91, mempool.cpp:29-196): one 4KB-aligned region per pool, carved into
// fixed-size blocks tracked by a uint64 bitmap (64 blocks per word, ctz scan),
// contiguous multi-block allocation, batched n-way allocation, double-free
// detection, and an `MM` front that manages multiple pools and signals when a
// new pool should be added (auto-extend). Differences from the reference:
// instead of ibv_reg_mr (no ibverbs on TPU VMs) the region is pinned with
// mlock() so the kernel never pages it out under the DCN send/recv data plane,
// and registration metadata is kept for the staging layer rather than for an
// RDMA rkey.
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <memory>
#include <vector>

namespace its {

// Reference constants (/root/reference/src/mempool.h:11-13).
constexpr double kBlockUsageRatio = 0.5;      // MM signals extend above this
constexpr size_t kExtendPoolSize = 10ull << 30;  // +10GB per auto-extend pool
constexpr size_t kExtendBlockSize = 64ull << 10;

class MemoryPool {
  public:
    // pool_size must be a multiple of block_size; block_size a power of two.
    MemoryPool(size_t pool_size, size_t block_size, bool pin = true);
    ~MemoryPool();

    MemoryPool(const MemoryPool&) = delete;
    MemoryPool& operator=(const MemoryPool&) = delete;

    // Allocate `size` bytes as ceil(size/block_size) *contiguous* blocks.
    // Returns nullptr when no contiguous run is free.
    void* allocate(size_t size);
    // Free a pointer previously returned by allocate(). Aborts the call (logs
    // and returns false) on double-free or foreign pointers.
    bool deallocate(void* ptr, size_t size);

    bool contains(const void* ptr) const {
        const char* p = static_cast<const char*>(ptr);
        return p >= base_ && p < base_ + pool_size_;
    }

    size_t block_size() const { return block_size_; }
    size_t total_blocks() const { return total_blocks_; }
    size_t used_blocks() const { return used_blocks_; }
    void* base() const { return base_; }
    bool pinned() const { return pinned_; }

  private:
    size_t find_free_run(size_t nblocks);
    void mark(size_t first_block, size_t nblocks, bool used);

    char* base_ = nullptr;
    size_t pool_size_;
    size_t block_size_;
    size_t total_blocks_;
    size_t used_blocks_ = 0;
    bool pinned_ = false;
    std::vector<uint64_t> bitmap_;  // 1 = used
};

// A (pool, ptr, size) lease. Deallocation goes back to the owning pool.
struct Lease {
    void* ptr = nullptr;
    size_t size = 0;
    MemoryPool* pool = nullptr;
};

// Multi-pool manager (reference MM, /root/reference/src/mempool.h:54-91).
class MM {
  public:
    MM(size_t initial_pool_size, size_t block_size, bool pin = true);

    // Batched n-way allocation: invokes cb(ptr, lease_index) for each of the n
    // leases as they are placed (reference MM::allocate's callback shape,
    // /root/reference/src/mempool.cpp:159). Returns false — allocating
    // nothing — if the full batch cannot be satisfied.
    bool allocate(size_t size, size_t n, const std::function<void(void*, size_t)>& cb,
                  std::vector<Lease>* out);
    void deallocate(const Lease& lease);
    // Free by raw pointer: finds the owning pool. Used by the KV layer.
    void deallocate(void* ptr, size_t size);

    // Add one more pool (auto-extend). Returns false on allocation failure.
    bool extend(size_t pool_size);

    // Fraction of blocks in use across all pools, in [0, 1].
    double usage() const;
    // True when usage is above kBlockUsageRatio — caller should extend.
    bool need_extend() const { return usage() > kBlockUsageRatio; }

    size_t block_size() const { return block_size_; }
    size_t total_bytes() const;
    size_t used_bytes() const;
    size_t pool_count() const { return pools_.size(); }
    bool pinned() const;

  private:
    size_t block_size_;
    bool pin_;
    std::vector<std::unique_ptr<MemoryPool>> pools_;
};

}  // namespace its
