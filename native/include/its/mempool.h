// Pinned host-DRAM memory pool with a first-fit bitmap allocator.
//
// TPU-native analogue of the reference's mempool (/root/reference/src/mempool.h
// :19-91, mempool.cpp:29-196): one 4KB-aligned region per pool, carved into
// fixed-size blocks tracked by a uint64 bitmap (64 blocks per word, ctz scan),
// contiguous multi-block allocation, batched n-way allocation, double-free
// detection, and an `MM` front that manages multiple pools and signals when a
// new pool should be added (auto-extend). Differences from the reference:
// instead of ibv_reg_mr (no ibverbs on TPU VMs) the region is pinned with
// mlock() so the kernel never pages it out under the DCN send/recv data plane,
// and registration metadata is kept for the staging layer rather than for an
// RDMA rkey.
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "its/bitmap_alloc.h"

namespace its {

// Reference constants (/root/reference/src/mempool.h:11-13).
constexpr double kBlockUsageRatio = 0.5;      // MM signals extend above this
constexpr size_t kExtendPoolSize = 10ull << 30;  // +10GB per auto-extend pool
constexpr size_t kExtendBlockSize = 64ull << 10;

class MemoryPool {
  public:
    // pool_size must be a multiple of block_size; block_size a power of two.
    // When shm_name is non-empty the region is a named POSIX shm segment
    // (shm_open + mmap MAP_SHARED) so same-host clients can map the pool and
    // move payloads with one memcpy, no socket — the TPU-host analogue of the
    // reference's GPUDirect zero-copy registration (ibv_reg_mr on device
    // pointers). Empty name = anonymous private memory as before.
    MemoryPool(size_t pool_size, size_t block_size, bool pin = true,
               const std::string& shm_name = "");
    ~MemoryPool();

    MemoryPool(const MemoryPool&) = delete;
    MemoryPool& operator=(const MemoryPool&) = delete;

    // Allocate `size` bytes as ceil(size/block_size) *contiguous* blocks.
    // Returns nullptr when no contiguous run is free.
    void* allocate(size_t size);
    // Free a pointer previously returned by allocate(). Aborts the call (logs
    // and returns false) on double-free or foreign pointers.
    bool deallocate(void* ptr, size_t size);

    bool contains(const void* ptr) const {
        const char* p = static_cast<const char*>(ptr);
        return p >= base_ && p < base_ + pool_size_;
    }

    size_t block_size() const { return block_size_; }
    size_t total_blocks() const { return alloc_.total; }
    size_t used_blocks() const { return alloc_.used; }
    void* base() const { return base_; }
    size_t size() const { return pool_size_; }
    bool pinned() const { return pinned_; }
    // Empty when the pool is anonymous (shm backing unavailable/disabled).
    const std::string& shm_name() const { return shm_name_; }

  private:
    char* base_ = nullptr;
    size_t pool_size_;
    size_t block_size_;
    bool pinned_ = false;
    bool shm_backed_ = false;
    int shm_fd_ = -1;  // kept open: holds the liveness flock for sweep
    std::string shm_name_;
    BitmapAlloc alloc_;  // shared first-fit bitmap (bitmap_alloc.h)
};

// A (pool, ptr, size) lease. Deallocation goes back to the owning pool.
struct Lease {
    void* ptr = nullptr;
    size_t size = 0;
    MemoryPool* pool = nullptr;
};

// Crash-safety for named shm segments: every live segment is tracked in a
// small global registry so the fatal-signal handler can unlink them (tmpfs
// pages otherwise outlive the process). SIGKILL can't be caught, so MM also
// sweeps /dev/shm for segments of dead pids at startup.
void shm_registry_add(const char* name);
void shm_registry_remove(const char* name);
void shm_registry_unlink_all();  // async-signal-safe
void shm_sweep_stale();

// One entry of the shm pool directory advertised to same-host clients.
struct PoolDirEntry {
    uint16_t pool_id = 0;
    std::string shm_name;  // empty = not mappable (anonymous pool)
    uint64_t size = 0;
};

// A (pool_id, offset) pair locating a block inside the shm directory.
struct PoolLoc {
    uint16_t pool_id = 0;
    uint64_t offset = 0;
    bool found = false;
};

// Multi-pool manager (reference MM, /root/reference/src/mempool.h:54-91).
class MM {
  public:
    // use_shm: back pools with named shm segments (falls back to anonymous
    // memory with a warning if /dev/shm is unavailable).
    MM(size_t initial_pool_size, size_t block_size, bool pin = true, bool use_shm = false);

    // Batched n-way allocation: invokes cb(ptr, lease_index) for each of the n
    // leases as they are placed (reference MM::allocate's callback shape,
    // /root/reference/src/mempool.cpp:159). Returns false — allocating
    // nothing — if the full batch cannot be satisfied.
    bool allocate(size_t size, size_t n, const std::function<void(void*, size_t)>& cb,
                  std::vector<Lease>* out);
    void deallocate(const Lease& lease);
    // Free by raw pointer: finds the owning pool. Used by the KV layer.
    void deallocate(void* ptr, size_t size);

    // Add one more pool (auto-extend). Returns false on allocation failure.
    bool extend(size_t pool_size);

    // Fraction of blocks in use across all pools, in [0, 1].
    double usage() const;
    // True when usage is above kBlockUsageRatio — caller should extend.
    bool need_extend() const { return usage() > kBlockUsageRatio; }

    size_t block_size() const { return block_size_; }
    size_t total_bytes() const;
    size_t used_bytes() const;
    size_t pool_count() const { return pools_.size(); }
    bool pinned() const;

    // Shm directory for the same-host fast path. Empty when use_shm is off
    // or the backing fell back to anonymous memory.
    std::vector<PoolDirEntry> pool_dir() const;
    bool shm_enabled() const { return shm_prefix_ != nullptr; }
    // Translate a pool pointer into (pool_id, offset) for the directory.
    PoolLoc locate(const void* ptr) const;

  private:
    std::string next_shm_name();

    size_t block_size_;
    bool pin_;
    std::unique_ptr<std::string> shm_prefix_;  // null = shm off
    std::vector<std::unique_ptr<MemoryPool>> pools_;
};

}  // namespace its
