// Clang thread-safety capability annotations (-Wthread-safety) — the
// native side of the ITS-R concurrency discipline (docs/static_analysis.md).
//
// The Python checker (tools/analysis/races.py) enforces declared guards on
// the client-side shared state; these macros give the C++ client/server
// structs the same contract, checked by clang's static analysis on the
// clang build path (the Makefile turns the warnings into errors there;
// gcc expands them to nothing). TSAN (`make -C native check-tsan`) covers
// dynamically what the annotations cannot express (the cross-process ring
// atomics in ring.h, which are __atomic by construction).
//
// Only the subset this codebase uses is defined; see
// https://clang.llvm.org/docs/ThreadSafetyAnalysis.html for semantics.
#pragma once

#if defined(__clang__) && defined(__has_attribute)
#define ITS_TS_ATTR(x) __attribute__((x))
#else
#define ITS_TS_ATTR(x)  // gcc / msvc: annotations compile away
#endif

// On a mutex member: this state may only be touched while `mu` is held.
#define ITS_GUARDED_BY(mu) ITS_TS_ATTR(guarded_by(mu))
// On a pointer member: the POINTED-TO data is guarded (the pointer itself
// may be read to compare/null-check without the lock).
#define ITS_PT_GUARDED_BY(mu) ITS_TS_ATTR(pt_guarded_by(mu))
// On a function: callers must hold `mu` (the `# its: requires[...]`
// contract, natively).
#define ITS_REQUIRES(mu) ITS_TS_ATTR(requires_capability(mu))
// On a function: it acquires/releases `mu` internally (lock wrappers).
#define ITS_ACQUIRE(mu) ITS_TS_ATTR(acquire_capability(mu))
#define ITS_RELEASE(mu) ITS_TS_ATTR(release_capability(mu))
// On a function: it must NOT be called with `mu` held (deadlock fences).
#define ITS_EXCLUDES(mu) ITS_TS_ATTR(locks_excluded(mu))
// Escape hatch for audited sites the analysis cannot see through
// (teardown paths where single-threadedness is established by joins).
#define ITS_NO_THREAD_SAFETY_ANALYSIS ITS_TS_ATTR(no_thread_safety_analysis)
