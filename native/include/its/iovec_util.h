// Resumable scatter/gather iovec helpers, shared by the server and client
// reactors. Both sides move payloads with partial readv/writev calls that must
// resume mid-iovec; keeping the offset arithmetic in one place means a fix
// lands everywhere at once.
#pragma once

#include <sys/uio.h>

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <vector>

namespace its {

// Progress cursor over a scatter list (receive side).
struct ScatterCursor {
    size_t idx = 0;
    size_t off = 0;

    void reset() { idx = off = 0; }
    bool done(const std::vector<iovec>& v) const { return idx >= v.size(); }

    // Fill `out` (capacity max_iov) with the remaining regions; returns count.
    size_t fill(const std::vector<iovec>& v, iovec* out, size_t max_iov) const {
        size_t n = std::min(v.size() - idx, max_iov);
        if (n == 0) return 0;
        out[0].iov_base = static_cast<char*>(v[idx].iov_base) + off;
        out[0].iov_len = v[idx].iov_len - off;
        for (size_t i = 1; i < n; i++) out[i] = v[idx + i];
        return n;
    }

    // Bytes not yet received across the remaining regions.
    uint64_t remaining(const std::vector<iovec>& v) const {
        uint64_t n = 0;
        for (size_t i = idx; i < v.size(); i++) n += v[i].iov_len;
        return n - off;
    }

    // Consume nbytes of progress.
    void advance(const std::vector<iovec>& v, size_t nbytes) {
        while (nbytes > 0) {
            size_t left = v[idx].iov_len - off;
            size_t take = std::min(nbytes, left);
            off += take;
            nbytes -= take;
            if (off == v[idx].iov_len) {
                idx++;
                off = 0;
            }
        }
    }
};

// Build the remaining iovec view of a framed message (fixed header, metadata
// body, then payload regions) given `sent` bytes already written.
// Returns the number of iovecs placed in `out`.
inline size_t build_send_iov(const void* hdr, size_t hdr_len, const std::vector<uint8_t>& body,
                             const std::vector<iovec>& payload, size_t sent, iovec* out,
                             size_t max_iov) {
    size_t niov = 0;
    size_t off = sent;
    if (off < hdr_len) {
        out[niov++] = iovec{const_cast<char*>(static_cast<const char*>(hdr)) + off,
                            hdr_len - off};
        off = 0;
    } else {
        off -= hdr_len;
    }
    if (niov < max_iov && off < body.size()) {
        out[niov++] = iovec{const_cast<uint8_t*>(body.data()) + off, body.size() - off};
        off = 0;
    } else {
        off -= std::min(off, body.size());
    }
    for (size_t i = 0; i < payload.size() && niov < max_iov; i++) {
        size_t len = payload[i].iov_len;
        if (off >= len) {
            off -= len;
            continue;
        }
        out[niov++] = iovec{static_cast<char*>(payload[i].iov_base) + off, len - off};
        off = 0;
    }
    return niov;
}

}  // namespace its
