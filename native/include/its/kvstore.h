// KV map + LRU eviction + refcounted block lifetime.
//
// TPU-native analogue of the reference's server-side state (kv_map, lru_queue,
// PTR intrusive refcount; /root/reference/src/infinistore.cpp:26-41,
// infinistore.h:24-39, evict_cache infinistore.cpp:223). Data-plane discipline
// matches the reference: all mutations happen on the single server reactor
// thread, so no locks are needed; std::shared_ptr supplies the PTR role —
// an in-flight streaming GET holds a reference so eviction cannot free a block
// mid-send (reference BulkWriteCtx, infinistore.cpp:282-287).
//
// One deliberate improvement over the reference: the LRU is a proper
// list+iterator structure with O(1) touch and no stale entries (the reference's
// lru_queue retains dead entries for overwritten keys until they age out,
// SURVEY.md §3.3 note).
#pragma once

#include <cstddef>
#include <cstdint>
#include <list>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "its/mempool.h"

namespace its {

// A committed KV block. Freed back to its pool when the last reference drops.
class Block {
  public:
    Block(MM* mm, void* ptr, size_t size) : mm_(mm), ptr_(ptr), size_(size) {}
    ~Block() {
        if (ptr_ != nullptr) mm_->deallocate(ptr_, size_);
    }
    Block(const Block&) = delete;
    Block& operator=(const Block&) = delete;

    void* data() const { return ptr_; }
    size_t size() const { return size_; }

  private:
    MM* mm_;
    void* ptr_;
    size_t size_;
};

using BlockRef = std::shared_ptr<Block>;

class KVStore {
  public:
    explicit KVStore(MM* mm) : mm_(mm) {}

    // Insert/overwrite. Called only after the payload transfer completed —
    // commit-on-completion, no partially-visible keys (SURVEY.md §3.3).
    void commit(const std::string& key, BlockRef block);

    // Lookup + LRU touch. Returns nullptr when missing.
    BlockRef get(const std::string& key);
    // Lookup without touching the LRU.
    BlockRef peek(const std::string& key) const;
    bool exists(const std::string& key) const;

    // Remove listed keys; returns how many were present.
    size_t remove(const std::vector<std::string>& keys);
    // Drop everything; returns prior count.
    size_t purge();
    size_t size() const { return map_.size(); }

    // Longest-prefix match: binary search for the last present key, assuming
    // the prefix property (keys[i] present => keys[j<i] present) — reference
    // Client::get_match_last_index (/root/reference/src/infinistore.cpp:786-798).
    // Returns -1 when keys[0] is absent.
    int32_t match_last_index(const std::vector<std::string>& keys) const;

    // If pool usage >= max_ratio, evict LRU entries until usage <= min_ratio
    // (reference evict_cache, /root/reference/src/infinistore.cpp:223).
    // Returns evicted entry count.
    size_t evict(double min_ratio, double max_ratio);

  private:
    struct Entry {
        BlockRef block;
        std::list<std::string>::iterator lru_it;
    };

    MM* mm_;
    std::unordered_map<std::string, Entry> map_;
    std::list<std::string> lru_;  // front = most recently used
};

}  // namespace its
