// KV map + LRU eviction + refcounted block lifetime.
//
// TPU-native analogue of the reference's server-side state (kv_map, lru_queue,
// PTR intrusive refcount; /root/reference/src/infinistore.cpp:26-41,
// infinistore.h:24-39, evict_cache infinistore.cpp:223). Data-plane discipline
// matches the reference: all mutations happen on the single server reactor
// thread, so no locks are needed; std::shared_ptr supplies the PTR role —
// an in-flight streaming GET holds a reference so eviction cannot free a block
// mid-send (reference BulkWriteCtx, infinistore.cpp:282-287).
//
// One deliberate improvement over the reference: the LRU is a proper
// list+iterator structure with O(1) touch and no stale entries (the reference's
// lru_queue retains dead entries for overwritten keys until they age out,
// SURVEY.md §3.3 note).
#pragma once

#include <cstddef>
#include <cstdint>
#include <functional>
#include <list>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "its/mempool.h"
#include "its/spillfile.h"

namespace its {

// A committed KV block. Freed back to its pool when the last reference drops.
class Block {
  public:
    Block(MM* mm, void* ptr, size_t size) : mm_(mm), ptr_(ptr), size_(size) {}
    ~Block() {
        if (ptr_ != nullptr) mm_->deallocate(ptr_, size_);
    }
    Block(const Block&) = delete;
    Block& operator=(const Block&) = delete;

    void* data() const { return ptr_; }
    size_t size() const { return size_; }

  private:
    MM* mm_;
    void* ptr_;
    size_t size_;
};

using BlockRef = std::shared_ptr<Block>;

class KVStore {
  public:
    // spill: optional file-backed tier (spillfile.h). With it, eviction
    // demotes LRU entries to the file instead of dropping them — capacity
    // beyond RAM, the tier the reference only aspired to
    // (reference docs/source/design.rst:36) — and get() promotes them back
    // into a RAM pool on access. nullptr (or !spill->ok()) = off: eviction
    // drops, exactly the reference's behavior.
    explicit KVStore(MM* mm, SpillFile* spill = nullptr)
        : mm_(mm), spill_(spill != nullptr && spill->ok() ? spill : nullptr) {}
    ~KVStore() { purge(); }

    // Insert/overwrite. Called only after the payload transfer completed —
    // commit-on-completion, no partially-visible keys (SURVEY.md §3.3).
    void commit(const std::string& key, BlockRef block);

    // Lookup + LRU touch. Returns nullptr when missing — AND, with a spill
    // tier, when a spilled entry cannot be promoted back into RAM right now
    // (the entry and its bytes SURVIVE, still spilled; callers should
    // surface resource pressure, not a miss).
    BlockRef get(const std::string& key);
    bool exists(const std::string& key) const;

    // Re-put fast path for the sliced put engine (server.cpp): when `key`
    // is RAM-resident, exactly `size` bytes, and this store holds the ONLY
    // reference (no in-flight GET pins the block), return the block after
    // an LRU touch so the caller can copy the new payload straight into it
    // — skipping the alloc + commit + old-block-free cycle of a re-put.
    // Returns nullptr otherwise (caller takes the legacy path). The
    // returned reference briefly raises use_count to 2; the caller must
    // finish the copy and drop it within the same reactor slice so
    // snapshot isolation for concurrently pinned readers holds (nothing
    // else runs inside a slice on the single-threaded reactor).
    BlockRef overwrite_slot(const std::string& key, size_t size);
    // Const eligibility probe for overwrite_slot (no LRU touch, no ref
    // taken): the put alloc phase uses it to skip pre-allocating blocks
    // for keys the copy phase expects to overwrite in place. Advisory
    // only — eligibility can lapse between slices (eviction, a reader
    // pinning the block), so the copy phase re-checks via overwrite_slot.
    bool overwrite_eligible(const std::string& key, size_t size) const;

    // Remove listed keys; returns how many were present.
    size_t remove(const std::vector<std::string>& keys);
    // Drop everything; returns prior count.
    size_t purge();
    size_t size() const { return map_.size(); }

    // Longest-prefix match: binary search for the last present key, assuming
    // the prefix property (keys[i] present => keys[j<i] present) — reference
    // Client::get_match_last_index (/root/reference/src/infinistore.cpp:786-798).
    // Returns -1 when keys[0] is absent.
    int32_t match_last_index(const std::vector<std::string>& keys) const;

    // If pool usage >= max_ratio, evict LRU entries until usage <= min_ratio
    // (reference evict_cache, /root/reference/src/infinistore.cpp:223).
    // With a spill tier, "evict" means demote-to-file; only when the file is
    // also full are the oldest spilled entries dropped for real.
    // Returns the number of entries demoted or dropped.
    size_t evict(double min_ratio, double max_ratio);

    // Reclaim the single LRU-coldest RAM entry (demote with a spill tier,
    // drop without). Returns false when no RAM-resident entries remain.
    // Lets the allocator free exactly what a large batch needs instead of
    // failing with OOM once the ratio-driven pass runs dry (the reference
    // 507s in that case even with reclaimable entries present).
    bool evict_one();

    // Promotion RAM allocator override: the server routes this through its
    // configured policy (on-demand evict ratios + auto_increase pool
    // extension), so promotion behaves exactly like any other allocation.
    // Unset = allocate from MM with a conservative evict-and-retry.
    using RamAlloc = std::function<bool(size_t, std::vector<Lease>*)>;
    void set_promote_alloc(RamAlloc fn) { promote_alloc_ = std::move(fn); }

    // Spill-tier observability (all zero when the tier is off).
    size_t spilled_entries() const { return spill_lru_.size(); }
    size_t spilled_bytes() const { return spill_ != nullptr ? spill_->used_bytes() : 0; }
    size_t spill_capacity() const { return spill_ != nullptr ? spill_->total_bytes() : 0; }
    uint64_t spill_promotions() const { return promotions_; }
    uint64_t spill_drops() const { return spill_drops_; }

  private:
    struct Entry {
        BlockRef block;                  // set when resident in RAM
        int64_t spill_off = -1;          // set when demoted to the file
        // size_t, not u32: block sizes are u64 on the wire (tcp_put payload),
        // and a truncated size here would desync free_slot/promote for
        // >=4GiB values — silent corruption, not an error.
        size_t spill_size = 0;
        std::list<std::string>::iterator lru_it;  // in lru_ or spill_lru_
        bool spilled() const { return block == nullptr && spill_off >= 0; }
    };

    void release_entry(Entry& e);  // frees the spill slot if any
    bool demote(const std::string& key, Entry& e);
    BlockRef promote(const std::string& key,
                     std::unordered_map<std::string, Entry>::iterator it);
    bool drop_oldest_spilled();

    MM* mm_;
    SpillFile* spill_;
    RamAlloc promote_alloc_;
    std::unordered_map<std::string, Entry> map_;
    std::list<std::string> lru_;        // RAM-resident entries; front = MRU
    std::list<std::string> spill_lru_;  // spilled entries; front = MRU
    uint64_t promotions_ = 0;
    uint64_t spill_drops_ = 0;
};

}  // namespace its
