// Server data plane: single-threaded epoll reactor.
//
// TPU-native analogue of the reference's libuv server
// (/root/reference/src/infinistore.cpp — Client state machine :55-109, on_read
// :887, handle_request :837, register_server :990). The reference grafts libuv
// onto uvloop inside the Python process and moves payloads with server-initiated
// one-sided RDMA; TPU VMs have no ibverbs, so here the data plane is
// cooperative zero-copy socket I/O on the DCN: requests carry metadata bodies,
// payloads are scattered straight between the socket and pinned pool blocks
// with readv/writev (no intermediate copies), and the server runs its own
// reactor thread started from Python via the C API (no uvloop dependency).
//
// Concurrency discipline matches the reference ("single thread right now",
// infinistore.cpp:1): every kv/pool mutation happens on the reactor thread.
// Control-plane calls from Python are marshalled onto the loop through an
// eventfd + closure queue and wait on a future.
#pragma once

#include <netinet/in.h>
#include <sys/uio.h>

#include <atomic>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "its/kvstore.h"
#include "its/mempool.h"
#include "its/protocol.h"
#include "its/thread_safety.h"

namespace its {

struct ServerConfig {
    std::string bind_addr = "0.0.0.0";
    int service_port = 22345;
    size_t prealloc_bytes = 16ull << 30;   // reference default 16GB prealloc
    size_t block_size = 64ull << 10;       // reference minimal_allocate_size 64KB
    bool auto_increase = false;            // add pools when usage > 50%
    size_t extend_pool_bytes = kExtendPoolSize;
    bool pin_memory = true;
    // On-demand eviction thresholds (reference hardcodes 0.8/0.95,
    // /root/reference/src/infinistore.cpp:52-53).
    double evict_min_ratio = 0.8;
    double evict_max_ratio = 0.95;
    // Back pools with named shm segments so same-host clients can move
    // payloads with one memcpy instead of the socket (degrades to anonymous
    // memory + socket path automatically when /dev/shm is unavailable).
    bool enable_shm = true;
    // Egress cap per accepted connection in MB/s via SO_MAX_PACING_RATE
    // (caps the server->client GET direction; the client-side knob caps
    // PUTs). 0 = unlimited. See ClientConfig::pacing_rate_mbps.
    uint32_t pacing_rate_mbps = 0;
    // File-backed spill tier (spillfile.h): evicted blocks demote to an
    // mmap'd file in spill_dir instead of being dropped, and promote back
    // on access — capacity beyond RAM. Empty dir or 0 bytes = off (evict
    // drops, the reference's behavior).
    std::string spill_dir;
    size_t spill_bytes = 0;
    // Reactor fairness: one-RTT segment ops (PutFrom/GetInto) run at most
    // ~this many bytes of pool/spill memcpy work per event-loop tick, then
    // yield so other connections are served between slices. Keeps an
    // innocent hot-path read's p99 within ~2x its uncontended value while a
    // spill-heavy batch churns (bench.py contended_* keys). Internal tuning
    // knob (C++-level; not surfaced through the CLI).
    size_t slice_bytes = 128ull << 10;
    // QoS two-level slice scheduler (docs/qos.md). While FOREGROUND work is
    // live — a foreground sliced op pending, or any foreground op seen
    // within the last bg_cooldown_us (hysteresis: engine reads arrive in
    // waves; without the cooldown, background work resumes into the tail of
    // a wave and its completions wake the background client mid-wave) — a
    // BACKGROUND-tagged op's slices are deferred, EXCEPT that one
    // background slice always runs per bg_aging_us of deferral: the
    // starvation-proof aging escape guarantees background >= slice_bytes
    // per bg_aging_us of progress under a permanent foreground flood, so
    // it always drains. Only engages when a tagged background op exists;
    // an all-untagged workload runs the exact pre-QoS FIFO round-robin.
    uint64_t bg_cooldown_us = 500;
    uint64_t bg_aging_us = 500;
};

// Per-op service counters (SURVEY.md §5.1: the reference has no tracing at
// all; we make latency/throughput first-class). Histogram buckets are log2 of
// microseconds: bucket i covers [2^i, 2^(i+1)) us.
struct OpStats {
    // HDR-style histogram: 32 sub-buckets per octave caps quantization
    // error at ~2% (base-2 octaves, 2^(1/32) ~= 1.022 steps) at 2048*8
    // bytes per op — the resolution the derived p50/p99 gauges and the
    // /metrics infinistore_op_duration_us histogram export inherit
    // (docs/observability.md).
    static constexpr int kSubBits = 5;
    static constexpr int kBuckets = 2048;

    uint64_t count = 0;
    uint64_t errors = 0;
    uint64_t bytes_in = 0;
    uint64_t bytes_out = 0;
    uint64_t total_us = 0;
    uint64_t lat_buckets[kBuckets] = {0};

    void record(uint64_t us, uint64_t in_bytes, uint64_t out_bytes, bool ok);
    double percentile_us(double q) const;
    double p50_us() const { return percentile_us(0.50); }
    double p99_us() const { return percentile_us(0.99); }
    // Inclusive upper bound (Prometheus `le`) of bucket ``idx`` in us.
    static uint64_t bucket_le_us(int idx);
};

// One traced op's server-side tick record (docs/observability.md): the
// reactor stamps these for any op whose metadata carried a non-zero trace
// id, into a bounded ring exported through stats_json()["trace"]. Stage
// names on the shared vocabulary: recv_us = server_recv, first/last_us =
// first_slice/last_slice (tracing.SERVER_TICK_STAGES).
struct TraceTick {
    uint64_t trace_id = 0;
    uint64_t parent_id = 0;  // the client span the op rode (wire trace_parent)
    uint8_t op = 0;
    uint8_t prio = 0;
    bool ok = true;
    uint64_t recv_us = 0;   // request fully read, op dispatched
    uint64_t first_us = 0;  // first payload/slice unit of work
    uint64_t last_us = 0;   // last payload/slice unit of work
    uint64_t done_us = 0;   // response enqueued (or error recorded)
    uint64_t bytes = 0;     // payload bytes moved (either direction)
};

class Server {
  public:
    explicit Server(const ServerConfig& config);
    ~Server();

    // Bind + listen + spawn the reactor thread. Returns false on bind failure.
    bool start();
    void stop();
    bool running() const { return running_.load(); }
    int port() const { return bound_port_; }  // actual port (0 in config = ephemeral)

    // Thread-safe control plane: each call runs its body on the reactor thread
    // and blocks the caller until done.
    size_t kvmap_len();
    size_t purge();
    size_t evict(double min_ratio, double max_ratio);
    double usage();
    std::string stats_json();

  private:
    struct Conn;

    void loop();
    void post(std::function<void()> fn);     // enqueue onto reactor, no wait
    void call(std::function<void()> fn);     // enqueue + wait for completion
    void accept_ready();
    void conn_readable(Conn* c);
    void conn_writable(Conn* c);
    void close_conn(Conn* c);
    void dispatch(Conn* c);
    void handle_put_batch(Conn* c);
    void handle_get_batch(Conn* c);
    void handle_tcp_put(Conn* c);
    void handle_shm(Conn* c);
    void handle_simple(Conn* c);
    // Descriptor-ring copy engine (docs/descriptor_ring.md): pop published
    // descriptors out of every attached submission ring into per-conn
    // pending queues (freeing the slots — backpressure relief), start them
    // through the same budget-sliced SegCont machinery the socket segment
    // ops use (QoS classes, aging, trace ticks all preserved), and finish
    // by publishing a completion-ring entry instead of a socket response.
    void handle_ring_attach(Conn* c);
    void drain_rings();
    bool drain_ring_conn(Conn* c);  // false = ring poisoned, close the conn
    void start_ring_descs(Conn* c);
    void start_ring_desc(Conn* c, uint8_t op, uint64_t token, SegBatchMeta m);
    void ring_push_cqe(Conn* c, uint64_t token, uint32_t status, uint64_t bytes);
    void ring_finish(Conn* c, uint32_t status, uint64_t bytes);
    bool alloc_blocks(size_t size, size_t n, std::vector<Lease>* leases);
    // Budget-sliced segment ops (see ServerConfig::slice_bytes).
    void queue_cont(Conn* c);
    void suspend_for_cont(Conn* c);
    void run_cont_slice(Conn* c);
    void run_getloc_slice(Conn* c);
    void run_putalloc_slice(Conn* c);
    // Shared promote+pin slice for GetLoc and GetInto's pin phase; the
    // validator rejects a pinned block (replies kStatusInvalidReq).
    enum class PinResult { kDone, kYield, kFinished };
    PinResult pin_slice(Conn* c,
                        const std::function<bool(size_t, const BlockRef&)>& validate);
    void finish_cont(Conn* c, uint32_t status);
    void arm_read(Conn* c, bool want_read);
    void finish_payload(Conn* c);
    void send_status(Conn* c, uint32_t status);
    void send_resp(Conn* c, uint32_t status, std::vector<uint8_t> body,
                   std::vector<iovec> payload, std::vector<BlockRef> refs);
    void send_loc_resp(Conn* c, ShmLocResp& resp,
                       const std::vector<PoolDirEntry>& dir);
    bool shm_mappable(const void* ptr, const std::vector<PoolDirEntry>& dir,
                      PoolLoc* out);
    void flush_out(Conn* c);
    void arm(Conn* c, bool want_write);
    bool ensure_capacity(size_t need_bytes);

    ServerConfig config_;
    std::unique_ptr<MM> mm_;
    std::unique_ptr<SpillFile> spill_;  // may be null (tier off)
    std::unique_ptr<KVStore> kv_;

    int epoll_fd_ = -1;
    int listen_fd_ = -1;
    int wake_fd_ = -1;
    int bound_port_ = 0;
    std::thread thread_;
    std::atomic<bool> running_{false};
    std::atomic<bool> stop_requested_{false};

    std::mutex posted_mu_;
    std::vector<std::function<void()>> posted_ ITS_GUARDED_BY(posted_mu_);

    std::unordered_map<int, std::unique_ptr<Conn>> conns_;
    // Connections with a suspended sliced segment op, split by QoS class.
    // With no BACKGROUND op suspended the foreground queue behaves exactly
    // like the old single cont_queue_; with one, foreground slices run
    // first and background slices run only when foreground is quiet
    // (cont_fg_ empty AND the bg_cooldown_us window expired) or the
    // time-based aging escape fires (see ServerConfig::bg_aging_us and
    // run_cont_pass).
    std::deque<Conn*> cont_fg_;
    std::deque<Conn*> cont_bg_;
    // Monotonic stamps driving the two-level scheduler: the last moment
    // foreground work was seen (op dispatch or fg slice — starts the
    // cooldown window) and the last background slice (drives the
    // time-based aging guarantee).
    uint64_t last_fg_us_ = 0;
    uint64_t last_bg_slice_us_ = 0;
    // Per-class QoS counters, exported under "qos" in stats_json().
    struct QosCounters {
        uint64_t fg_ops = 0;          // tagged-or-default foreground ops dispatched
        uint64_t bg_ops = 0;          // background-tagged ops dispatched
        uint64_t fg_slices = 0;       // sliced-work quanta run per class
        uint64_t bg_slices = 0;
        uint64_t bg_preempted = 0;    // slice slots (passes) bg sat out behind fg
        uint64_t bg_aged = 0;         // bg slices run via the aging escape
        void note(uint8_t prio) {
            (prio == kPriorityBackground ? bg_ops : fg_ops)++;
        }
    } qos_;
    // Count an op dispatch against its class; a foreground op also starts
    // the background-deferral cooldown window.
    void note_op(uint8_t prio);
    void run_cont_pass(int epoll_events_seen, int* idle_streak);
    void run_one_slice(Conn* c, std::deque<Conn*>* queue);
    // True while background work must yield: a foreground sliced op is
    // pending, or foreground activity was seen within the cooldown window.
    bool bg_must_defer() const;
    // Reclaim budgeting for sliced allocations: when slice_mode_ is set,
    // alloc_blocks skips the ratio sweep, caps demote iterations at
    // slice_reclaim_left_, and reports a cap-hit via slice_capped_ (the
    // caller retries next slice instead of failing the op with 507).
    bool slice_mode_ = false;
    bool slice_capped_ = false;
    size_t slice_reclaim_left_ = 0;
    // RAII scope for the above: an exception between set and clear would
    // otherwise leave slice_mode_ stuck true server-wide (silently skipping
    // the ratio evict sweep for every later allocation).
    struct SliceBudget {
        Server* s;
        SliceBudget(Server* srv, size_t budget_blocks) : s(srv) {
            s->slice_mode_ = true;
            // Slack beyond the nominal budget: a few demotes may free no
            // RAM (entries pinned by in-flight ops) through no fault of
            // this op's sizing.
            s->slice_reclaim_left_ = budget_blocks + 4;
        }
        ~SliceBudget() { s->slice_mode_ = false; }
    };
    // close_conn() defers destruction here so callers holding a Conn* across
    // a close (e.g. readable -> dispatch -> flush -> error) never dangle; the
    // reactor clears it between epoll batches.
    std::vector<std::unique_ptr<Conn>> graveyard_;
    std::unordered_map<uint8_t, OpStats> stats_;
    uint64_t conns_accepted_ = 0;

    // Descriptor-ring plane: connections with an attached ring (drained
    // every loop pass) and the server half of the ring ledger
    // (stats_json()["ring"] → /metrics infinistore_ring_*).
    std::vector<Conn*> ring_conns_;
    struct RingCounters {
        uint64_t attached = 0;         // lifetime successful attaches
        uint64_t descriptors = 0;      // descriptors (ops) consumed from SQs
        uint64_t doorbells_rx = 0;     // client->server doorbell frames
        uint64_t cq_doorbells_tx = 0;  // server->client doorbell frames
        uint64_t completions = 0;      // CQEs published
        uint64_t bad_descriptors = 0;  // rejected per-descriptor (CQE 400)
        uint64_t torn_descriptors = 0; // generation-tag mismatches (fatal)
        // PR 16 mechanism ledger (docs/descriptor_ring.md): multi-op batch
        // slots consumed / ops unpacked from them, the adaptive pre-park
        // poll outcomes (hit = a descriptor landed inside the busy-poll
        // window, arm = the window expired and the park proceeded), and
        // CQEs published while the client reactor was awake — no doorbell
        // frame needed (the elision the small-op path banks on).
        uint64_t batch_slots = 0;      // kRingSlotFlagBatch slots consumed
        uint64_t batch_ops = 0;        // ops unpacked from batch slots
        uint64_t poll_hits = 0;        // poll window caught a descriptor
        uint64_t poll_arms = 0;        // poll window expired; parked
        uint64_t doorbell_elided = 0;  // CQE published to an awake client
    } ring_counters_;
    // Mirror of run_cont_pass's idle streak for the ring copy engine's
    // adaptive slice budget (see run_cont_slice).
    int idle_streak_ = 0;
    // Adaptive pre-park poll state (ring.h ring_poll_budget): EWMA of
    // descriptor inter-arrival gaps + last-arrival stamp. Reactor-only.
    uint64_t ring_gap_ewma_us_ = 0;
    uint64_t ring_last_desc_us_ = 0;

    // Reactor loop-pass phase accounting (docs/observability.md,
    // profiling section): cumulative CLOCK_MONOTONIC microseconds per
    // pass phase — the epoll wait itself, socket event dispatch,
    // descriptor-ring drain, the sliced-cont pass (slice execution plus
    // its QoS scheduling decisions), and everything else (ring
    // park/doorbell arming, timeout bookkeeping, graveyard). Exported
    // through stats_json()["prof"] -> /metrics infinistore_prof_*; the
    // cost is six vDSO clock reads per pass, amortized against the real
    // work a non-idle pass does (an idle reactor blocks 200ms per pass).
    // Reactor-thread-only, read via call() like every other counter.
    struct ProfCounters {
        uint64_t passes = 0;
        uint64_t wait_us = 0;    // blocked in epoll_wait
        uint64_t events_us = 0;  // accept/readable/writable dispatch
        uint64_t rings_us = 0;   // drain_rings descriptor consumption
        uint64_t slices_us = 0;  // run_cont_pass (slices + QoS decisions)
        uint64_t poll_us = 0;    // adaptive pre-park SQ busy-poll window
        uint64_t other_us = 0;   // park/doorbell arming, bookkeeping
    } prof_;

    // Trace tick ring (docs/observability.md): server_recv/first_slice/
    // last_slice/done stamps for ops that carried a wire trace context.
    // Reactor-thread-only (stats_json reads it via call()); untraced ops
    // never touch it beyond one per-op branch.
    static constexpr int kTraceRing = 128;
    TraceTick trace_ring_[kTraceRing];
    uint64_t trace_next_ = 0;     // total ticks ever recorded
    uint64_t trace_dropped_ = 0;  // ticks the full ring overwrote
    // Per-op stamps live on the Conn (one op in flight per connection);
    // these helpers are no-ops for untraced ops (trace_id == 0).
    void trace_begin(Conn* c, uint64_t trace_id, uint64_t parent, uint8_t prio);
    void trace_slice(Conn* c);
    void trace_finish(Conn* c, uint64_t bytes, bool ok);
};

}  // namespace its
