// Wire protocol: fixed request/response headers + compact schema'd bodies.
//
// TPU-native analogue of the reference's protocol layer
// (/root/reference/src/protocol.h:38-95 + five FlatBuffers schemas): a packed
// fixed header {magic, op, body_size}, one-byte op codes, HTTP-like status
// codes, and a 4MB cap on metadata bodies. Instead of FlatBuffers we use a
// hand-rolled little-endian encoding (length-prefixed strings and vectors)
// mirrored exactly by infinistore_tpu/wire.py — the environment has no flatc,
// and the bodies are small and fixed in shape, so a schema compiler buys
// nothing. Payload bytes (KV-block data) are never serialized: they are moved
// by scatter-gather I/O directly between sockets and registered memory, which
// is how the design keeps the reference's "no extra copy" property without
// one-sided RDMA (SURVEY.md §5.8).
#pragma once

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace its {

constexpr uint32_t kMagic = 0x49545055;  // "ITPU" little-endian
// Metadata bodies are capped, mirroring the reference's 4MB protocol buffers
// (/root/reference/src/protocol.h:28 PROTOCOL_BUFFER_SIZE).
constexpr uint32_t kMaxBodySize = 4u << 20;
// Op codes (one byte on the wire).
enum Op : uint8_t {
    kOpPutBatch = 'W',       // batched block write; client streams payload after body
    kOpGetBatch = 'R',       // batched block read; server streams payload after resp body
    kOpTcpPut = 'P',         // single-key put (reference OP_TCP_PUT)
    kOpTcpGet = 'G',         // single-key get (reference OP_TCP_GET)
    kOpCheckExist = 'E',     // key existence probe
    kOpMatchLastIdx = 'M',   // longest-prefix match index (binary search)
    kOpDeleteKeys = 'D',     // delete a list of keys
    kOpStat = 'S',           // server stats snapshot (selftest support)
    // Same-host shm fast path: payload moves by direct memcpy between client
    // memory and the server's shm-backed pools; only metadata rides the
    // socket. The allocate-then-write shape mirrors the reference's (unused)
    // RdmaAllocateResponse schema (reference src/allocate_response.fbs) and
    // its server-pull RDMA design: the server still owns placement, and keys
    // commit only after the transfer completes.
    kOpShmHello = 'H',       // capability probe -> shm pool directory
    kOpPutAlloc = 'p',       // batched write phase 1: allocate, return locations
    kOpPutCommit = 'c',      // batched write phase 2: publish keys
    kOpGetLoc = 'g',         // batched read: pin blocks, return locations
    kOpRelease = 'r',        // drop a ticket's pinned blocks; NO response
    // One-RTT server-pull variant: the client's registered staging region is
    // itself a named shm segment the server maps. Put = server pulls blocks
    // out of the client segment (the exact shape of the reference's
    // server-initiated RDMA READ, reference docs/source/design.rst:51-52);
    // get = server pushes into it (the RDMA WRITE analogue). One message per
    // batch, no tickets, placement and copy both server-owned.
    kOpRegSegment = 'B',     // register a client shm segment {id, name, size}
    kOpPutFrom = 'F',        // pull blocks from client segment offsets; commit
    kOpGetInto = 'I',        // push stored blocks into client segment offsets
    // Descriptor-ring data plane (docs/descriptor_ring.md): batched segment
    // ops post as fixed-slot descriptors in a client-created shm ring
    // instead of per-op socket writes. The socket is demoted to a doze/wake
    // doorbell in both directions — written only when the other side has
    // parked itself (the empty->non-empty discipline the PR 2 completion
    // ring established for the native->Python eventfd).
    kOpRingAttach = 'Q',     // register a descriptor-ring shm segment {name, size}
    kOpRingDoorbell = 'q',   // submission-ring doorbell; empty body, NO response
};

// Two-class QoS service model (docs/qos.md): FOREGROUND (decode-blocking
// reads) vs BACKGROUND (saves, replica mirrors, spill-feeding churn).
// FOREGROUND is the default and encodes NOTHING — the priority-off wire
// format is byte-identical to the pre-QoS one; BACKGROUND rides an optional
// trailing tag byte on BatchMeta/SegBatchMeta, which old decoders never read
// (body length is explicit) and old encoders never produce.
enum Priority : uint8_t {
    kPriorityForeground = 0,
    kPriorityBackground = 1,
};

// End-to-end op tracing (docs/observability.md): a per-op trace context —
// u64 trace id + u64 parent span id — rides BatchMeta/SegBatchMeta as a
// SECOND trailing optional extension AFTER the QoS priority byte. An
// untraced op (trace_id == 0, the default) appends nothing — byte-identical
// to the pre-trace format — and a traced op also emits the priority byte
// (even 0) so the trailing-optional walk stays unambiguous. Real trace ids
// are never zero (tracing.py _new_id).
constexpr uint64_t kTraceIdNone = 0;

// HTTP-like status codes (reference /root/reference/src/protocol.h:55-62).
enum Status : uint32_t {
    // Unsolicited server->client frame: "your completion ring has entries"
    // (the CQ doorbell). Carries no body/payload and is NOT matched to an
    // in-flight request — the client drains its completion ring and keeps
    // reading. 1xx (informational) so it can never collide with a real
    // response status.
    kStatusRingEvent = 100,
    kStatusOk = 200,
    kStatusTaskAccepted = 202,
    kStatusInvalidReq = 400,
    kStatusKeyNotFound = 404,
    kStatusRetry = 408,
    kStatusInternal = 500,
    kStatusUnavailable = 503,
    kStatusOutOfMemory = 507,
    // Present-but-unpromotable: the key is ALIVE in the spill tier but the
    // server's RAM is too pressured to promote it for this op right now —
    // "cold but alive", distinct from 507 (genuine allocation exhaustion)
    // and from 404 (data absent). Callers retry smaller/later or read it
    // through the pooled cold tier; tier stats count it as a demotion hit,
    // never a miss (docs/tiering.md).
    kStatusColdTier = 512,
};

// ---------------------------------------------------------------------------
// Descriptor ring (docs/descriptor_ring.md). The client creates one shm
// segment per connection laid out as [RingCtrl | SQ slots | CQ entries |
// per-SQ-slot meta arena] and registers it with kOpRingAttach; from then on
// batched segment ops (kOpPutFrom / kOpGetInto) post as RingSlot descriptors
// whose meta region holds the op's ordinary SegBatchMeta encoding — the
// EXACT body bytes the socket path would have carried, so decode, QoS
// tagging and the trace-context extensions are shared with the wire format.
// Completion rides back as a RingCqe. These structs are memory-mapped by
// BOTH processes, so field NAMES and widths are protocol surface exactly
// like the packed wire headers; the wire-drift checker (ITS-W004/W005)
// holds them in lockstep with their wire.py twins.
// ---------------------------------------------------------------------------

constexpr uint32_t kRingMagic = 0x52535449;  // "ITSR" little-endian
constexpr uint32_t kRingVersion = 1;
// Default submission-slot count (power of two; ClientConfig::ring_slots
// overrides). The completion ring is sized equal and the client bounds its
// in-flight ring ops to it, so the CQ can never overflow.
constexpr uint32_t kRingSqSlots = 64;
// Per-slot descriptor-body capacity: bounds one posted op's SegBatchMeta
// encoding (~1700 64-char keys + offsets). Bigger bodies fall back to the
// socket path (counted, never an error).
constexpr uint32_t kRingMetaStride = 128u << 10;
// Multi-op batch slots (docs/descriptor_ring.md): a slot whose flags carry
// kRingSlotFlagBatch packs a whole coalesced flush into its meta arena —
// RingBatchHdr, then count x (RingBatchEntry + that op's SegBatchMeta
// bytes). The slot's token is the BASE of a contiguous token group: op i
// completes with its own RingCqe under token base+i, so the CQE format and
// the client's completion matching are unchanged. kRingBatchMaxOps bounds
// the per-slot op count on both sides (a header claiming more is a bad
// descriptor, answered with error CQEs for the whole group).
constexpr uint8_t kRingSlotFlagBatch = 0x1;
constexpr uint16_t kRingBatchMaxOps = 64;
// RingCtrl's reserved span at the segment head (page-sized so the slot
// arrays start page-aligned).
constexpr uint32_t kRingCtrlSpan = 4096;

#pragma pack(push, 1)
struct ReqHeader {
    uint32_t magic;
    uint8_t op;
    uint32_t body_size;
};
struct RespHeader {
    uint32_t status;
    uint32_t body_size;    // op-specific response body (sizes, counts, ...)
    uint64_t payload_size; // raw KV payload streamed after the body
};
// Ring control block (segment offset 0). The four cursors are monotonic
// 64-bit sequence numbers (never wrapped; slot index = seq % slots). Fields
// are naturally aligned by construction so cross-process atomic access
// (__atomic builtins) is valid despite the packed layout.
struct RingCtrl {
    uint32_t magic;        // kRingMagic
    uint32_t version;      // kRingVersion
    uint32_t sq_slots;     // submission slots (power of two)
    uint32_t cq_slots;     // completion slots (== sq_slots today)
    uint32_t slot_bytes;   // sizeof(RingSlot) echo — cross-build guard
    uint32_t cqe_bytes;    // sizeof(RingCqe) echo
    uint32_t meta_stride;  // per-SQ-slot descriptor-body capacity
    uint32_t flags;        // reserved (0)
    uint64_t sq_tail;      // client publish cursor (release store)
    uint64_t sq_head;      // server consume cursor — slot reusable below it
    uint64_t cq_tail;      // server publish cursor
    uint64_t cq_head;      // client consume cursor — entry reusable below it
    uint32_t srv_waiting;  // server parked in epoll; poster must doorbell
    uint32_t cli_waiting;  // client reactor parked; completer must doorbell
};
// One posted descriptor. The slot's meta region (meta_stride bytes at
// ring_meta_off + index * meta_stride) holds meta_len bytes of the op's
// SegBatchMeta encoding; ``gen`` is the publish tag, written LAST with
// release order as sequence+1 — a slot whose gen does not match the
// consumer's expected sequence is torn/corrupt and rejected.
struct RingSlot {
    uint64_t gen;       // publish tag: submission sequence + 1
    uint64_t token;     // completion-matching token (client-chosen)
    uint32_t meta_len;  // SegBatchMeta body bytes in the slot's meta region
    uint8_t op;         // kOpPutFrom or kOpGetInto
    uint8_t flags;      // reserved (0)
    uint16_t reserved;  // reserved (0)
};
// One completion. Same publish discipline as RingSlot (gen = sequence + 1,
// release-stored last).
struct RingCqe {
    uint64_t gen;       // publish tag: completion sequence + 1
    uint64_t token;     // echoes RingSlot::token
    uint64_t bytes;     // payload bytes moved (either direction)
    uint32_t status;    // HTTP-like op status
    uint32_t flags;     // reserved (0)
};
// Batch-slot meta-arena header: first bytes of a kRingSlotFlagBatch slot's
// meta region. Followed by ``count`` RingBatchEntry records, each
// immediately trailed by its op's SegBatchMeta encoding.
struct RingBatchHdr {
    uint16_t count;     // ops packed in this slot (1..kRingBatchMaxOps)
    uint16_t reserved;  // reserved (0)
};
// One op inside a batch slot. Op i's completion token is slot token + i.
struct RingBatchEntry {
    uint32_t meta_len;  // SegBatchMeta bytes following this entry
    uint8_t op;         // kOpPutFrom or kOpGetInto
    uint8_t flags;      // reserved (0)
    uint16_t reserved;  // reserved (0)
};
#pragma pack(pop)

static_assert(sizeof(ReqHeader) == 9, "wire header must stay packed");
static_assert(sizeof(RespHeader) == 16, "wire resp header must stay packed");
static_assert(sizeof(RingCtrl) == 72, "ring control block layout is shared state");
static_assert(sizeof(RingSlot) == 24, "ring slot layout is shared state");
static_assert(sizeof(RingCqe) == 32, "ring cqe layout is shared state");
static_assert(sizeof(RingBatchHdr) == 4, "batch header layout is shared state");
static_assert(sizeof(RingBatchEntry) == 8, "batch entry layout is shared state");

// ---------------------------------------------------------------------------
// Encoding helpers. Little-endian, length-prefixed. Python mirror: wire.py.
// ---------------------------------------------------------------------------

class WireWriter {
  public:
    explicit WireWriter(std::vector<uint8_t>& out) : out_(out) {}

    void u8(uint8_t v) { out_.push_back(v); }
    void u16(uint16_t v) { append(&v, 2); }
    void u32(uint32_t v) { append(&v, 4); }
    void u64(uint64_t v) { append(&v, 8); }
    void i32(int32_t v) { append(&v, 4); }
    void str(const std::string& s) {
        if (s.size() > UINT16_MAX) throw std::invalid_argument("key too long");
        u16(static_cast<uint16_t>(s.size()));
        append(s.data(), s.size());
    }
    void str_list(const std::vector<std::string>& v) {
        u32(static_cast<uint32_t>(v.size()));
        for (const auto& s : v) str(s);
    }

  private:
    void append(const void* p, size_t n) {
        const uint8_t* b = static_cast<const uint8_t*>(p);
        out_.insert(out_.end(), b, b + n);
    }
    std::vector<uint8_t>& out_;
};

class WireReader {
  public:
    WireReader(const uint8_t* data, size_t size) : data_(data), size_(size) {}

    uint8_t u8() { return *take(1); }
    uint16_t u16() { return load<uint16_t>(); }
    uint32_t u32() { return load<uint32_t>(); }
    uint64_t u64() { return load<uint64_t>(); }
    int32_t i32() { return load<int32_t>(); }
    std::string str() {
        uint16_t n = u16();
        const uint8_t* p = take(n);
        return std::string(reinterpret_cast<const char*>(p), n);
    }
    std::vector<std::string> str_list() {
        uint32_t n = u32();
        std::vector<std::string> v;
        v.reserve(n);
        for (uint32_t i = 0; i < n; i++) v.push_back(str());
        return v;
    }
    bool done() const { return pos_ == size_; }

  private:
    template <typename T>
    T load() {
        T v;
        std::memcpy(&v, take(sizeof(T)), sizeof(T));
        return v;
    }
    const uint8_t* take(size_t n) {
        if (pos_ + n > size_) throw std::out_of_range("wire body truncated");
        const uint8_t* p = data_ + pos_;
        pos_ += n;
        return p;
    }
    const uint8_t* data_;
    size_t size_;
    size_t pos_ = 0;
};

// ---------------------------------------------------------------------------
// Request bodies (reference schemas: meta_request.fbs, tcp_payload_request.fbs,
// delete_keys.fbs, get_match_last_index.fbs).
// ---------------------------------------------------------------------------

// Batched block read/write metadata (reference RemoteMetaRequest,
// /root/reference/src/meta_request.fbs:2-8 — minus rkey/remote_addrs, which
// were one-sided-RDMA artifacts; on the cooperative TCP/DCN data plane the
// payload rides the same socket in key order).
struct BatchMeta {
    uint32_t block_size = 0;
    std::vector<std::string> keys;
    uint8_t priority = kPriorityForeground;  // optional trailing byte; 0 = untagged
    uint64_t trace_id = kTraceIdNone;  // optional trailing trace context; 0 = untraced
    uint64_t trace_parent = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.u32(block_size);
        w.str_list(keys);
        if (priority != kPriorityForeground || trace_id != kTraceIdNone) w.u8(priority);
        if (trace_id != kTraceIdNone) {
            w.u64(trace_id);
            w.u64(trace_parent);
        }
    }
    static BatchMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        BatchMeta m;
        m.block_size = r.u32();
        m.keys = r.str_list();
        if (!r.done()) m.priority = r.u8();
        if (!r.done()) {
            m.trace_id = r.u64();
            m.trace_parent = r.u64();
        }
        return m;
    }
};

// Single-key put metadata (reference TCPPayloadRequest).
struct TcpPutMeta {
    std::string key;
    uint64_t value_length = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.str(key);
        w.u64(value_length);
    }
    static TcpPutMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        TcpPutMeta m;
        m.key = r.str();
        m.value_length = r.u64();
        return m;
    }
};

// Single key (TcpGet / CheckExist).
struct KeyMeta {
    std::string key;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.str(key);
    }
    static KeyMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        KeyMeta m;
        m.key = r.str();
        return m;
    }
};

// Ticket body (PutCommit / Release).
struct TicketMeta {
    uint64_t ticket = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.u64(ticket);
    }
    static TicketMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        TicketMeta m;
        m.ticket = r.u64();
        return m;
    }
};

// Shm pool directory entry + block location, shared by the PutAlloc/GetLoc
// response bodies and the ShmHello response.
struct ShmPool {
    uint16_t pool_id = 0;
    std::string name;
    uint64_t size = 0;
};
struct ShmLoc {
    uint16_t pool_id = 0;
    uint64_t offset = 0;
    uint32_t size = 0;  // stored block size (GetLoc); block_size echo (PutAlloc)
};

// Response body for PutAlloc and GetLoc: {ticket, locations, pool directory}.
// The directory carries every mappable pool so clients can map auto-extended
// pools on demand without a re-handshake.
struct ShmLocResp {
    uint64_t ticket = 0;
    std::vector<ShmLoc> locs;
    std::vector<ShmPool> pools;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.u64(ticket);
        w.u32(static_cast<uint32_t>(locs.size()));
        for (const auto& l : locs) {
            w.u16(l.pool_id);
            w.u64(l.offset);
            w.u32(l.size);
        }
        w.u16(static_cast<uint16_t>(pools.size()));
        for (const auto& p : pools) {
            w.u16(p.pool_id);
            w.str(p.name);
            w.u64(p.size);
        }
    }
    static ShmLocResp decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        ShmLocResp m;
        m.ticket = r.u64();
        uint32_t n = r.u32();
        m.locs.reserve(n);
        for (uint32_t i = 0; i < n; i++) {
            ShmLoc l;
            l.pool_id = r.u16();
            l.offset = r.u64();
            l.size = r.u32();
            m.locs.push_back(l);
        }
        uint16_t np = r.u16();
        m.pools.reserve(np);
        for (uint16_t i = 0; i < np; i++) {
            ShmPool p;
            p.pool_id = r.u16();
            p.name = r.str();
            p.size = r.u64();
            m.pools.push_back(p);
        }
        return m;
    }
};

// Client shm segment registration (RegSegment).
struct SegMeta {
    uint16_t seg_id = 0;
    std::string name;
    uint64_t size = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.u16(seg_id);
        w.str(name);
        w.u64(size);
    }
    static SegMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        SegMeta m;
        m.seg_id = r.u16();
        m.name = r.str();
        m.size = r.u64();
        return m;
    }
};

// Descriptor-ring segment registration (RingAttach): the client names the
// shm segment holding its RingCtrl + slot arrays; geometry rides in the
// mapped RingCtrl itself (single source — the attach body never duplicates
// it, so the two can't drift).
struct RingMeta {
    std::string name;
    uint64_t size = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.str(name);
        w.u64(size);
    }
    static RingMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        RingMeta m;
        m.name = r.str();
        m.size = r.u64();
        return m;
    }
};

// One-RTT batched op against a registered client segment (PutFrom / GetInto):
// block i lives at segment offset offsets[i].
struct SegBatchMeta {
    uint32_t block_size = 0;
    uint16_t seg_id = 0;
    std::vector<std::string> keys;
    std::vector<uint64_t> offsets;
    uint8_t priority = kPriorityForeground;  // optional trailing byte; 0 = untagged
    uint64_t trace_id = kTraceIdNone;  // optional trailing trace context (see BatchMeta)
    uint64_t trace_parent = 0;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.u32(block_size);
        w.u16(seg_id);
        w.str_list(keys);
        w.u32(static_cast<uint32_t>(offsets.size()));
        for (uint64_t off : offsets) w.u64(off);
        if (priority != kPriorityForeground || trace_id != kTraceIdNone) w.u8(priority);
        if (trace_id != kTraceIdNone) {
            w.u64(trace_id);
            w.u64(trace_parent);
        }
    }
    static SegBatchMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        SegBatchMeta m;
        m.block_size = r.u32();
        m.seg_id = r.u16();
        m.keys = r.str_list();
        uint32_t n = r.u32();
        m.offsets.reserve(n);
        for (uint32_t i = 0; i < n; i++) m.offsets.push_back(r.u64());
        if (!r.done()) m.priority = r.u8();
        if (!r.done()) {
            m.trace_id = r.u64();
            m.trace_parent = r.u64();
        }
        return m;
    }
};

// Key list (DeleteKeys / GetMatchLastIndex).
struct KeyListMeta {
    std::vector<std::string> keys;

    void encode(std::vector<uint8_t>& out) const {
        WireWriter w(out);
        w.str_list(keys);
    }
    static KeyListMeta decode(const uint8_t* data, size_t size) {
        WireReader r(data, size);
        KeyListMeta m;
        m.keys = r.str_list();
        return m;
    }
};

}  // namespace its
