// Client library: one TCP/DCN connection to a store server, with a dedicated
// reactor thread completing pipelined async operations.
//
// TPU-native analogue of the reference's client
// (/root/reference/src/libinfinistore.h:63-119, libinfinistore.cpp): the same
// surface — connect/close, register_mr, async batched block write/read against
// one registered base pointer with (key, offset) lists and a uniform
// block_size, sync control ops (check_exist, get_match_last_index,
// delete_keys), single-key TCP put/get — and the same completion architecture
// (a background thread fires callbacks; the Python layer marshals them onto
// asyncio with call_soon_threadsafe). What changed: the reference's CQ-polling
// thread over ibverbs completions becomes an epoll reactor over the socket;
// payload moves by scatter-gather writev/readv directly between user-registered
// memory and the socket, preserving the zero-copy-on-client property of the
// one-sided RDMA design without ibverbs.
#pragma once

#include <sys/uio.h>

#include <atomic>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "its/iovec_util.h"
#include "its/protocol.h"
#include "its/thread_safety.h"

namespace its {

struct ClientConfig {
    std::string host = "127.0.0.1";
    int port = 22345;
    int connect_timeout_ms = 10000;
    // Deadline for every synchronous control op (tcp_put/get, check_exist,
    // match_last_index, delete, stat): a stalled-but-connected server makes
    // the call fail with kStatusUnavailable instead of hanging the caller
    // forever. <= 0 waits indefinitely (not recommended).
    int op_timeout_ms = 30000;
    // Try the same-host shm fast path at connect: map the server's shm-backed
    // pools and move batched payloads with one memcpy instead of the socket.
    // Degrades automatically to the socket path when the server is remote or
    // shm-less.
    bool enable_shm = true;
    // Egress cap for this connection in MB/s via SO_MAX_PACING_RATE (TCP
    // internal pacing; no qdisc needed). 0 = unlimited. Production use:
    // fairness on a shared DCN link. Test use: emulating a bandwidth-capped
    // cross-host stream on loopback to exercise connection striping.
    uint32_t pacing_rate_mbps = 0;
    // Descriptor-ring data plane (docs/descriptor_ring.md): when the shm
    // fast path is up, create a shared submission/completion ring and post
    // batched segment ops as descriptors instead of per-op socket writes.
    // Degrades automatically (socket path, byte-identical) when shm is
    // unavailable or the server declines the attach.
    bool enable_ring = true;
    // Submission-slot count (power of two; 0 = kRingSqSlots default). The
    // completion ring is sized equal and in-flight ring ops are bounded by
    // it; a full ring falls back to the socket path for that op (counted —
    // ring-full backpressure, never an error).
    uint32_t ring_slots = 0;
};

using CompletionCb = void (*)(void* ctx, int code);

class Connection {
  public:
    explicit Connection(const ClientConfig& config);
    ~Connection();

    // Blocking TCP connect + reactor spawn. Returns 0 or -errno.
    int connect();
    void close();
    bool connected() const { return connected_.load(); }

    // Pin + register a local memory region; batched ops validate their base
    // pointer against registered regions (reference register_mr,
    // /root/reference/src/libinfinistore.cpp:728; unregistered base is an
    // error, :602-605).
    int register_mr(void* ptr, size_t size);
    // Drop a transfer-scoped registration (most recent region with this
    // base). In-flight ops referencing the region are unaffected: iovecs are
    // captured at submit time.
    int unregister_mr(void* ptr);

    // Allocate a shm-backed staging region the SERVER maps too: batched ops
    // whose base pointer lies inside it use the one-RTT server-pull/push
    // path (PutFrom/GetInto) — the closest analogue of the reference's
    // one-sided RDMA against client-registered memory. Returns nullptr when
    // the server is remote or shm-less (caller falls back to a normal
    // buffer + register_mr). Freed at close().
    void* alloc_shm_mr(size_t size);

    // Async batched block write: for each i, send block_size bytes from
    // base_ptr+offsets[i] under keys[i]. cb fires from the reactor thread with
    // an HTTP-like status. Returns 0 on submit, -1 if not connected /
    // unregistered base. ``priority`` is the QoS class tag (protocol.h
    // Priority): kPriorityForeground (default) leaves the wire bytes
    // untouched; kPriorityBackground marks the op for the server's
    // two-level slice scheduler (docs/qos.md).
    // ``trace_id``/``trace_span``: per-op trace context (protocol.h
    // kTraceIdNone) — 0/0 (the default) leaves the wire bytes untouched; a
    // non-zero trace id rides the trailing trace extension and the server
    // reactor stamps recv/slice/done ticks for it into its trace ring
    // (docs/observability.md). ``trace_span`` is the CLIENT span the
    // server ticks hang under (wire field trace_parent).
    int put_batch_async(const std::vector<std::string>& keys,
                        const std::vector<uint64_t>& offsets, uint32_t block_size,
                        void* base_ptr, CompletionCb cb, void* ctx,
                        uint8_t priority = kPriorityForeground,
                        uint64_t trace_id = kTraceIdNone, uint64_t trace_span = 0);
    // Async batched block read into base_ptr+offsets[i].
    int get_batch_async(const std::vector<std::string>& keys,
                        const std::vector<uint64_t>& offsets, uint32_t block_size,
                        void* base_ptr, CompletionCb cb, void* ctx,
                        uint8_t priority = kPriorityForeground,
                        uint64_t trace_id = kTraceIdNone, uint64_t trace_span = 0);

    // Sync batched ops: same pipeline, but the calling thread blocks on the
    // completion (promise wait — no event-loop hop). This is the low-latency
    // path for single-block fetches: the asyncio bridge costs ~2 extra
    // context switches per op on a single-core host, which dominates a
    // same-host block fetch (measured: ~58us async vs ~20us sync p50 at
    // 4KB). Returns 0 on success, -status on failure. On op_timeout_ms
    // expiry returns -kStatusUnavailable and abandons the wait; the op may
    // still complete server-side, and the base region must stay registered
    // and alive until close() (true for staging pools by construction).
    int put_batch(const std::vector<std::string>& keys, const std::vector<uint64_t>& offsets,
                  uint32_t block_size, void* base_ptr,
                  uint8_t priority = kPriorityForeground,
                  uint64_t trace_id = kTraceIdNone, uint64_t trace_span = 0);
    int get_batch(const std::vector<std::string>& keys, const std::vector<uint64_t>& offsets,
                  uint32_t block_size, void* base_ptr,
                  uint8_t priority = kPriorityForeground,
                  uint64_t trace_id = kTraceIdNone, uint64_t trace_span = 0);

    // Sync ops (safe to call from any thread; they ride the same pipeline).
    int tcp_put(const std::string& key, const void* data, size_t size);
    // On success fills *out (malloc'd — caller frees with free()) and *out_size.
    int tcp_get(const std::string& key, uint8_t** out, size_t* out_size);
    // Returns 1 = exists, 0 = missing, negative status on error.
    int check_exist(const std::string& key);
    // Returns match index (>= -1); INT32_MIN on transport error.
    int32_t get_match_last_index(const std::vector<std::string>& keys);
    // Returns number deleted, or negative status.
    int64_t delete_keys(const std::vector<std::string>& keys);
    // Server stats snapshot (JSON). Empty on error.
    std::string stat_json();

    // True when the same-host shm fast path is active for batched ops.
    bool shm_active() const { return shm_ok_.load(); }

    // True when the descriptor-ring data plane is active (shm fast path up,
    // ring segment attached by the server).
    bool ring_active() const { return ring_ok_.load(); }
    // Shm name of this connection's ring segment (empty when inactive).
    // Introspection surface for tests/tools — the torn-descriptor tests map
    // the segment by name and tamper with it.
    std::string ring_name() const;
    // Client-side ring ledger: descriptors posted, submission doorbells
    // sent (empty->non-empty / doze transitions only), ring-full and
    // meta-too-big socket fallbacks, completions consumed from the CQ.
    void ring_counters(uint64_t* posted, uint64_t* doorbells, uint64_t* full_fallbacks,
                       uint64_t* meta_fallbacks, uint64_t* completions) const;
    // PR 16 mechanism ledger: multi-op batch slots published / ops packed
    // into them (batch_ops / batch_slots = mean flush size the bench gates
    // on), and the reactor's adaptive poll-then-park outcome counts —
    // poll_hits (a completion landed inside the busy-poll window: no park,
    // no doorbell) vs poll_arms (window expired with ops still in flight;
    // the reactor parked and armed the doorbell).
    void ring_poll_counters(uint64_t* batch_slots, uint64_t* batch_ops,
                            uint64_t* poll_hits, uint64_t* poll_arms) const;

    // Multi-op batch grouping (docs/descriptor_ring.md). Between begin and
    // end, async batched segment ops posted by the SAME thread accumulate
    // instead of publishing one slot each; end() greedily packs the group
    // into kRingSlotFlagBatch slots (one per meta-arena-load), publishes
    // them with ONE tail store + at most one doorbell, and routes whatever
    // does not fit (ring full / in-flight cap) to the socket path, counted
    // as the usual fallbacks. Sync ops and other threads bypass an open
    // group entirely. No-ops when the ring is down; never errors.
    void ring_group_begin();
    void ring_group_end();

    // Event-fd completion ring (the low-fixed-cost asyncio bridge). When a
    // completion fd is set, async batched ops submitted with cb == nullptr
    // and ctx != nullptr complete by pushing (ctx-as-token, code) into a
    // ring and signalling the fd — the Python event loop wakes via its own
    // epoll (add_reader) and drains the whole ring in one pass, instead of
    // paying one GIL acquisition + call_soon_threadsafe hop PER op. The fd
    // is owned by the caller (typically an os.eventfd); it is never closed
    // here.
    void set_completion_fd(int fd);
    // Pop up to cap completions into tokens/codes; returns the count.
    int drain_completions(uint64_t* tokens, int32_t* codes, int cap);
    // Coalescing counters: completions pushed into the ring vs eventfd
    // writes issued. The fd is written only on an empty->non-empty ring
    // transition (a completion landing while a wakeup is already armed
    // piggybacks on it — this is what lets a burst of small gets share one
    // loop wakeup instead of arming one each), so pushed/signalled is the
    // mean completion batch per wakeup the bench reports.
    void completion_counters(uint64_t* pushed, uint64_t* signalled) const;

  private:
    struct Request;
    struct SyncState;
    struct ShmMap {
        char* base = nullptr;
        size_t size = 0;
    };

    void reactor();
    int submit(std::unique_ptr<Request> req);
    // Route a built batched request: descriptor ring when eligible (segment
    // op, ring active, fits a slot, ring not full), else the socket pipeline.
    int submit_any(std::unique_ptr<Request> req);
    void fail_all(int code);
    bool flush_send();
    bool read_ready();
    // take_body: move rbody_ into the sync state — ONLY when this request's
    // response was actually received (fail_all / abandoned-drop completions
    // must not steal a different in-flight response's partially read body).
    void complete(std::unique_ptr<Request> req, int code, bool take_body);
    // timeout_ms < 0 = use config_.op_timeout_ms (which <= 0 waits forever);
    // on timeout returns kStatusUnavailable and abandons the wait (a late
    // response completes into shared state, FIFO matching stays intact).
    uint32_t sync_roundtrip(std::unique_ptr<Request> req, std::vector<uint8_t>* body_out,
                            uint8_t** payload_out, size_t* payload_size_out,
                            int timeout_ms = -1);
    bool base_registered(const void* base, size_t span) const;
    // Shared request construction for the batched data plane (async + sync).
    std::unique_ptr<Request> build_put(const std::vector<std::string>& keys,
                                       const std::vector<uint64_t>& offsets,
                                       uint32_t block_size, void* base_ptr,
                                       uint8_t priority, uint64_t trace_id,
                                       uint64_t trace_span);
    std::unique_ptr<Request> build_get(const std::vector<std::string>& keys,
                                       const std::vector<uint64_t>& offsets,
                                       uint32_t block_size, void* base_ptr,
                                       uint8_t priority, uint64_t trace_id,
                                       uint64_t trace_span);
    void shm_handshake();
    // Create + attach the descriptor ring (after a successful shm
    // handshake). Failure is silent degradation to the socket path.
    void ring_setup();
    void ring_teardown();
    // Try to post ``req`` as a ring descriptor. Returns 0 when posted (the
    // request is parked in ring_inflight_ until its CQE arrives) or -1 when
    // the caller must fall back to the socket path (ring full / in-flight
    // cap / descriptor body exceeds meta_stride — counted).
    int try_ring_post(std::unique_ptr<Request>* req);
    // Publish one plain (single-op) slot. Caller holds dring_mu_ and has
    // verified space + body fit. Returns whether the server needs a doorbell.
    bool ring_publish_one_locked(std::unique_ptr<Request> req)
        ITS_REQUIRES(dring_mu_);
    // Reactor-side: drain the completion ring, completing parked requests.
    // Returns false on a corrupt ring (fails the connection).
    bool drain_cq();
    char* map_pool(uint16_t pool_id, const std::string& name, uint64_t size);
    // Reactor-side: handle a PutAlloc/GetLoc response. Returns the request
    // back if it must be re-queued (put commit phase), nullptr when done.
    std::unique_ptr<Request> shm_phase(std::unique_ptr<Request> req, uint32_t status);
    void queue_release(uint64_t ticket);

    ClientConfig config_;
    int fd_ = -1;
    int wake_fd_ = -1;
    int epoll_fd_ = -1;
    std::thread thread_;
    std::atomic<bool> connected_{false};
    std::atomic<bool> stop_{false};

    std::mutex submit_mu_;
    std::vector<std::unique_ptr<Request>> submitted_ ITS_GUARDED_BY(submit_mu_);

    // Seqlock-style counter bracketing every reactor region that touches
    // caller memory (writev from tx_payload, readv into rx_addrs, shm
    // memcpys): odd = inside a region. A timed-out sync waiter sets
    // SyncState::abandoned and then waits for this to be even, so after
    // sync_roundtrip returns the reactor can never again touch the caller's
    // buffers (regions check the flag AFTER going odd — Dekker pairing).
    std::atomic<uint64_t> io_seq_{0};
    // Abandoned one-RTT segment op: the reactor must fail the connection
    // (see SyncState::seg_op).
    std::atomic<bool> poison_{false};

    // Reactor-owned state.
    std::deque<std::unique_ptr<Request>> sendq_;
    std::deque<std::unique_ptr<Request>> awaiting_;

    // Response read state.
    RespHeader rhdr_{};
    size_t rhdr_got_ = 0;
    std::vector<uint8_t> rbody_;
    size_t rbody_got_ = 0;
    std::vector<iovec> rx_iov_;
    ScatterCursor rx_cur_;
    uint64_t rx_discard_ = 0;
    bool rx_failed_ = false;  // payload rejected client-side (drained, op errors)
    bool resp_in_progress_ = false;
    bool rx_setup_done_ = false;

    mutable std::mutex mr_mu_;
    std::vector<std::pair<const char*, size_t>> regions_ ITS_GUARDED_BY(mr_mu_);

    // Completion ring (see set_completion_fd). Pushed by the reactor (and by
    // fail_all on close), drained by the owning event loop — and, at
    // teardown, by the closing thread.
    std::atomic<int> comp_fd_{-1};
    std::mutex ring_mu_;
    std::vector<std::pair<uint64_t, int32_t>> ring_ ITS_GUARDED_BY(ring_mu_);
    // Wakeup-coalescing counters (see completion_counters).
    std::atomic<uint64_t> comp_pushed_{0};
    std::atomic<uint64_t> comp_signalled_{0};

    // Client-owned shm staging segments (one-RTT path).
    struct ClientSeg {
        char* base = nullptr;
        size_t size = 0;
        uint16_t id = 0;
        std::string name;  // empty once unlinked (server declined)
        bool server_mapped = false;
    };
    std::vector<ClientSeg> client_segs_ ITS_GUARDED_BY(mr_mu_);
    const ClientSeg* find_seg(const void* base, size_t span) const;

    // Shm fast-path state. Written at connect (handshake) and by the reactor
    // (on-demand mapping of auto-extended pools); guarded for the overlap.
    std::atomic<bool> shm_ok_{false};
    mutable std::mutex shm_mu_;
    std::unordered_map<uint16_t, ShmMap> shm_pools_ ITS_GUARDED_BY(shm_mu_);

    // Descriptor-ring state (docs/descriptor_ring.md; "dring" because the
    // PR 2 completion ring above already owns the plain ring_/ring_mu_
    // names). The view and name are written once at connect (ring_setup)
    // and torn down in close(); submit-side cursors + the in-flight map are
    // guarded by dring_mu_ (producers are arbitrary caller threads; the
    // reactor erases on completion). CQ consumption is reactor-only.
    struct RingState;
    std::unique_ptr<RingState> dring_;
    std::atomic<bool> ring_ok_{false};
    mutable std::mutex dring_mu_;
    std::unordered_map<uint64_t, std::unique_ptr<Request>> ring_inflight_
        ITS_GUARDED_BY(dring_mu_);
    uint64_t ring_next_token_ ITS_GUARDED_BY(dring_mu_) = 1;
    uint64_t ring_sq_seq_ ITS_GUARDED_BY(dring_mu_) = 0;  // descriptors posted
    // Completions consumed: reactor-only by design (drain_cq runs on the
    // reactor thread; ring_teardown zeroes it under dring_mu_ after the
    // reactor stopped) — deliberately NOT capability-annotated.
    uint64_t ring_cq_seq_ = 0;
    // Ledger (ring_counters): posted descriptors, doorbells actually sent,
    // ring-full and oversized-meta socket fallbacks, CQ completions.
    std::atomic<uint64_t> ring_posted_{0};
    std::atomic<uint64_t> ring_doorbells_{0};
    std::atomic<uint64_t> ring_full_fallbacks_{0};
    std::atomic<uint64_t> ring_meta_fallbacks_{0};
    std::atomic<uint64_t> ring_completions_{0};

    // Multi-op batch grouping (ring_group_begin/end). Owned by the thread
    // that opened the group; posts from other threads (and all sync ops)
    // bypass the group and take the plain path.
    bool group_active_ ITS_GUARDED_BY(dring_mu_) = false;
    std::thread::id group_owner_ ITS_GUARDED_BY(dring_mu_);
    std::vector<std::unique_ptr<Request>> group_reqs_ ITS_GUARDED_BY(dring_mu_);
    // PR 16 ledger (ring_poll_counters).
    std::atomic<uint64_t> ring_batch_slots_{0};
    std::atomic<uint64_t> ring_batch_ops_{0};
    std::atomic<uint64_t> ring_poll_hits_{0};
    std::atomic<uint64_t> ring_poll_arms_{0};
    // Adaptive poll state: EWMA of inter-CQE gaps + last CQE timestamp.
    // Reactor-only (updated in drain_cq, read before parking) — unguarded
    // by design, like ring_cq_seq_.
    uint64_t ring_gap_ewma_us_ = 0;
    uint64_t ring_last_cqe_us_ = 0;
};

}  // namespace its
