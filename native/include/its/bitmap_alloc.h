// First-fit contiguous-run bitmap allocator, shared by the RAM pools
// (mempool.cpp) and the spill file (spillfile.cpp) — one implementation so
// an allocator fix lands in both tiers at once.
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace its {

struct BitmapAlloc {
    std::vector<uint64_t> bits;  // 1 = used
    size_t total = 0;
    size_t used = 0;

    void init(size_t nblocks) {
        total = nblocks;
        used = 0;
        bits.assign((nblocks + 63) / 64, 0);
    }

    bool is_used(size_t i) const { return (bits[i / 64] >> (i % 64)) & 1; }

    // First-fit scan. Fast path: skip fully-used words, find the first zero
    // bit with ctz (reference uses ctz the same way,
    // /root/reference/src/mempool.cpp:55-112), then verify run length.
    size_t find_free_run(size_t nblocks) const {
        size_t idx = 0;
        while (idx < total) {
            size_t word = idx / 64;
            if (bits[word] == ~0ull) {
                idx = (word + 1) * 64;
                continue;
            }
            uint64_t inv = ~bits[word] & (~0ull << (idx % 64));
            if (inv == 0) {
                idx = (word + 1) * 64;
                continue;
            }
            size_t start = word * 64 + static_cast<size_t>(__builtin_ctzll(inv));
            if (start >= total) break;
            size_t run = 0;
            while (run < nblocks && start + run < total) {
                if (is_used(start + run)) break;
                run++;
            }
            if (run == nblocks) return start;
            idx = start + run + 1;
        }
        return SIZE_MAX;
    }

    void mark(size_t first, size_t nblocks, bool set_used) {
        for (size_t i = first; i < first + nblocks; i++) {
            uint64_t bit = 1ull << (i % 64);
            if (set_used) {
                bits[i / 64] |= bit;
            } else {
                bits[i / 64] &= ~bit;
            }
        }
    }

    // Returns the first block of an allocated run, or SIZE_MAX.
    size_t alloc_run(size_t nblocks) {
        size_t start = find_free_run(nblocks);
        if (start == SIZE_MAX) return SIZE_MAX;
        mark(start, nblocks, true);
        used += nblocks;
        return start;
    }

    void free_run(size_t first, size_t nblocks) {
        mark(first, nblocks, false);
        used -= nblocks;
    }
};

}  // namespace its
