// Logging for the native core.
//
// TPU-native analogue of the reference's spdlog console logger
// (/root/reference/src/log.h:11-26, log.cpp:5-33): leveled macros where
// WARN/ERROR carry file:line, a runtime level setter, and a bridge so Python
// can route messages through the same sink. We use a plain stderr sink with an
// optional C callback (installed by the Python layer) instead of spdlog, which
// keeps the native core dependency-free.
#pragma once

#include <cstdarg>

namespace its {

enum class LogLevel : int {
    kDebug = 0,
    kInfo = 1,
    kWarning = 2,
    kError = 3,
    kOff = 4,
};

using LogSink = void (*)(int level, const char* msg);

void set_log_level(LogLevel level);
LogLevel log_level();
// Install a sink that replaces the default stderr writer (nullptr restores).
void set_log_sink(LogSink sink);
// printf-style; applies the level filter, then dispatches to the sink.
void log_msg(LogLevel level, const char* fmt, ...) __attribute__((format(printf, 2, 3)));

// Install SIGSEGV/ABRT/BUS/FPE/ILL handlers that print a backtrace before
// dying (reference utils.cpp:216-223). Idempotent.
void install_crash_handler();

}  // namespace its

#define ITS_LOG_DEBUG(fmt, ...) \
    ::its::log_msg(::its::LogLevel::kDebug, fmt, ##__VA_ARGS__)
#define ITS_LOG_INFO(fmt, ...) ::its::log_msg(::its::LogLevel::kInfo, fmt, ##__VA_ARGS__)
#define ITS_LOG_WARN(fmt, ...) \
    ::its::log_msg(::its::LogLevel::kWarning, "%s:%d " fmt, __FILE__, __LINE__, ##__VA_ARGS__)
#define ITS_LOG_ERROR(fmt, ...) \
    ::its::log_msg(::its::LogLevel::kError, "%s:%d " fmt, __FILE__, __LINE__, ##__VA_ARGS__)
