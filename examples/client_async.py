"""Fully-async 1000-key batch (reference example/client_async.py: 1000 keys
written/read with asyncio.gather over the async connection)."""

import asyncio

import numpy as np

from common import get_connection, parse_args


async def run(conn):
    n, block = 1000, 4 << 10
    src = np.random.randint(0, 256, size=n * block, dtype=np.uint8)
    dst = np.zeros_like(src)
    conn.register_mr(src)
    conn.register_mr(dst)

    await asyncio.gather(
        *(
            conn.write_cache_async([(f"async-{i}", i * block)], block, src.ctypes.data)
            for i in range(n)
        )
    )
    print(f"wrote {n} keys")
    await asyncio.gather(
        *(
            conn.read_cache_async([(f"async-{i}", i * block)], block, dst.ctypes.data)
            for i in range(n)
        )
    )
    assert np.array_equal(src, dst)
    print(f"read {n} keys, verified")


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        asyncio.run(run(conn))
    finally:
        cleanup()


if __name__ == "__main__":
    main()
