"""Cross-request prefix reuse through the KVConnector (reference scenario 2,
README.md:15-16: the extra-large KV pool with cross-node reuse; LMCache plays
this role for vLLM in the reference stack).

Request A prefills a long system prompt and saves its KV blocks. Request B
shares the system prompt but has a different user turn: the connector's
lookup finds the shared block-aligned prefix, load() fetches only those
blocks, and the engine prefills just the new suffix.
"""

import asyncio

import jax.numpy as jnp
import numpy as np

from common import get_connection, parse_args

from infinistore_tpu import KVConnector
from infinistore_tpu.tpu import PagedKVCacheSpec


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        spec = PagedKVCacheSpec(
            num_layers=4, num_blocks=32, block_tokens=8, num_kv_heads=2,
            head_dim=64, dtype=jnp.bfloat16,
        )
        connector = KVConnector(conn, spec, model_id="demo", max_blocks=8)

        system_prompt = list(range(1000, 1032))  # 4 blocks of 8 tokens
        req_a = system_prompt + [1, 2, 3, 4, 5, 6, 7, 8]  # 5 blocks

        # Request A: nothing cached -> engine prefills everything, then saves.
        assert connector.lookup(req_a) == 0
        caches = spec.make_caches()
        # (A real engine fills `caches` by running prefill; the flow is the
        # same either way.)
        block_ids_a = np.arange(5, dtype=np.int32)
        written = asyncio.run(connector.save(req_a, caches, block_ids_a))
        print(f"request A: saved {written} KV blocks to the store")

        # Request B: shares the 4 system-prompt blocks, new user turn.
        req_b = system_prompt + [9, 10, 11, 12, 13, 14, 15, 16]
        hit = connector.lookup(req_b)
        print(f"request B: {hit} of {len(req_b) // spec.block_tokens} blocks cached")
        assert hit == 4

        fresh = spec.make_caches()
        block_ids_b = np.arange(10, 15, dtype=np.int32)
        _, loaded = asyncio.run(connector.load(req_b, fresh, block_ids_b))
        print(f"request B: loaded {loaded} blocks; engine only prefills the last "
              f"{len(req_b) - loaded * spec.block_tokens} tokens")

        print(f"cleanup: dropped {connector.drop(req_a)} store keys")
    finally:
        cleanup()


if __name__ == "__main__":
    main()
