"""Batched write/read roundtrip (reference example/client.py: sync-over-async
RDMA write/read of block lists; the cuda/cpu source-destination combos become
host staging buffers on TPU VMs)."""

import asyncio

import numpy as np

from common import get_connection, parse_args


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        block_size = 64 << 10
        nblocks = 16
        src = np.random.randint(0, 256, size=nblocks * block_size, dtype=np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)

        blocks = [(f"example-key-{i}", i * block_size) for i in range(nblocks)]
        asyncio.run(conn.write_cache_async(blocks, block_size, src.ctypes.data))
        print(f"wrote {nblocks} x {block_size >> 10}KB blocks")

        asyncio.run(conn.read_cache_async(blocks, block_size, dst.ctypes.data))
        assert np.array_equal(src, dst)
        print("read back and verified")

        print("exists:", conn.check_exist("example-key-0"))
        print("deleted:", conn.delete_keys([k for k, _ in blocks]))
    finally:
        cleanup()


if __name__ == "__main__":
    main()
