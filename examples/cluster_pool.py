"""A KV pool spanning several servers (reference scenario 2, README.md:13-16,
scaled out: the reference serves its extra-large pool from ONE process and
leaves multi-node routing to LMCache; here the framework provides it).

Three local servers become one ClusterKVConnector. Prompts route by the hash
of their FIRST token block (rendezvous hashing), so every prompt sharing a
system prefix lands on the same server and per-server longest-prefix match
keeps working.

The pool is ELASTIC (docs/membership.md): a fourth server JOINs live — the
membership epoch bumps and the background resharder migrates only the
rendezvous-delta roots to it (~R/(N+1), BACKGROUND-tagged) — then one
member LEAVEs gracefully: its roots re-mirror to their promoted successors
before it is REMOVED, so stopping the node afterwards costs nothing.
Finally a member is killed WITHOUT ceremony to show the degrade policy and
per-member health attribution (docs/robustness.md).
"""

import asyncio
import os
import sys

import jax.numpy as jnp
import numpy as np

# Allow running straight from a repo checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its
from infinistore_tpu import ClusterKVConnector
from infinistore_tpu.tpu import PagedKVCacheSpec


def main():
    spec = PagedKVCacheSpec(
        num_layers=4, num_blocks=32, block_tokens=8, num_kv_heads=2,
        head_dim=64, dtype=jnp.bfloat16,
    )
    servers, conns = [], []
    for _ in range(3):
        srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
        conn = its.InfinityConnection(
            its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port,
                             log_level="error")
        )
        conn.connect()
        servers.append(srv)
        conns.append(conn)
    try:
        cluster = ClusterKVConnector(
            conns, spec, model_id="demo", max_blocks=8, degrade=True
        )

        # 12 prompts with distinct roots spread over the members.
        prompts = [
            [seed * 1000 + t for t in range(2 * spec.block_tokens)]
            for seed in range(12)
        ]
        for i, p in enumerate(prompts):
            caches = [
                (
                    jnp.full(spec.cache_shape, i + 1, spec.dtype),
                    jnp.full(spec.cache_shape, -(i + 1), spec.dtype),
                )
                for _ in range(spec.num_layers)
            ]
            asyncio.run(cluster.save(p, caches, np.array([0, 1], np.int32)))
        owners = [cluster.owner_index(p) for p in prompts]
        print("owner per prompt:", owners)
        print("members used:", sorted(set(owners)))

        hits = sum(cluster.lookup(p) for p in prompts)
        print(f"blocks cached across the pool: {hits}")

        # --- live JOIN: the pool grows without a restart ------------------
        srv4 = its.start_local_server(prealloc_bytes=64 << 20,
                                      block_bytes=16 << 10)
        conn4 = its.InfinityConnection(
            its.ClientConfig(host_addr="127.0.0.1", service_port=srv4.port,
                             log_level="error")
        )
        conn4.connect()
        servers.append(srv4)
        conns.append(conn4)
        view = cluster.add_member(conn4, wait=True)
        ms = cluster.membership_status()
        print(
            f"joined member 3: epoch={view.epoch} -> "
            f"{ms['membership_epoch']} (finalized), moved "
            f"{ms['reshard_moved_roots']} roots / "
            f"{ms['reshard_moved_keys']} keys "
            f"({ms['reshard_moved_bytes']} bytes, BACKGROUND), "
            f"pruned {ms['reshard_pruned_keys']} old copies, "
            f"debt={ms['reshard_debt_roots']}"
        )
        owners = [cluster.owner_index(p) for p in prompts]
        print("owner per prompt after join:", owners)

        # --- graceful LEAVE: re-mirror first, then stop the node ----------
        leaver = cluster.member_ids[1]
        cluster.remove_member(leaver, wait=True)
        ms = cluster.membership_status()
        print(
            f"drained {leaver}: epoch={ms['membership_epoch']}, "
            f"re-mirrored (lifetime moved={ms['reshard_moved_roots']} "
            f"roots), debt={ms['reshard_debt_roots']} -> node may stop"
        )
        servers[1].stop()  # free: every root already has R copies elsewhere
        hits = sum(cluster.lookup(p) for p in prompts)
        print(f"blocks cached after leave: {hits} (no loss)")

        # --- crash: kill one member WITHOUT ceremony ----------------------
        # Only its prompts degrade to misses (replicas=1 here; with
        # replicas=2 reads would fail over — tests/test_selfheal.py).
        owners = [cluster.owner_index(p) for p in prompts]
        victim = owners[0]  # owners come from the live placement
        servers[victim].stop()
        after = [cluster.lookup(p) for p in prompts]
        lost = sum(1 for o, h in zip(owners, after) if o == victim and h == 0)
        kept = sum(1 for o, h in zip(owners, after) if o != victim and h == 2)
        print(
            f"after stopping member {victim}: {lost} prompts degraded to "
            f"miss, {kept} still fully cached, degraded_ops="
            f"{cluster.degraded_ops}"
        )
        # The self-healing layer's attribution (docs/robustness.md): the
        # dead member's breaker opens after a few errors (later ops
        # fast-fail locally instead of burning timeouts), and health()
        # names the sick node — now alongside its membership state. With
        # replicas=2 the same death would cost NOTHING: reads fail over to
        # the mirror, and mark_dead() re-replicates in the background.
        health = cluster.health()
        for m in health["members"]:
            print(
                f"  {m['member_id']}: state={m['state']} "
                f"breaker={m['breaker_state']} errors={m['errors']} "
                f"fast_fails={m['fast_fails']} "
                f"degraded_ops={m['degraded_ops']}"
            )
        # Write the crashed member off: with replicas=1 its roots are
        # unrecoverable, and the resharder says so honestly.
        cluster.mark_dead(cluster.member_ids[victim], wait=True)
        ms = cluster.membership_status()
        print(
            f"marked dead: epoch={ms['membership_epoch']}, written-off "
            f"roots={ms['reshard_lost_roots']} (replicas=1; with "
            f"replicas=2 they would re-replicate instead)"
        )
    finally:
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass


if __name__ == "__main__":
    main()
