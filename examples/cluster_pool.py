"""A KV pool spanning several servers (reference scenario 2, README.md:13-16,
scaled out: the reference serves its extra-large pool from ONE process and
leaves multi-node routing to LMCache; here the framework provides it).

Three local servers become one ClusterKVConnector. Prompts route by the hash
of their FIRST token block (rendezvous hashing), so every prompt sharing a
system prefix lands on the same server and per-server longest-prefix match
keeps working. Stopping one server shows the degrade policy: its prompts
become cache misses (recompute), everyone else's keep hitting.
"""

import asyncio
import os
import sys

import jax.numpy as jnp
import numpy as np

# Allow running straight from a repo checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its
from infinistore_tpu import ClusterKVConnector
from infinistore_tpu.tpu import PagedKVCacheSpec


def main():
    spec = PagedKVCacheSpec(
        num_layers=4, num_blocks=32, block_tokens=8, num_kv_heads=2,
        head_dim=64, dtype=jnp.bfloat16,
    )
    servers, conns = [], []
    for _ in range(3):
        srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
        conn = its.InfinityConnection(
            its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port,
                             log_level="error")
        )
        conn.connect()
        servers.append(srv)
        conns.append(conn)
    try:
        cluster = ClusterKVConnector(
            conns, spec, model_id="demo", max_blocks=8, degrade=True
        )

        # 12 prompts with distinct roots spread over the members.
        prompts = [
            [seed * 1000 + t for t in range(2 * spec.block_tokens)]
            for seed in range(12)
        ]
        for i, p in enumerate(prompts):
            caches = [
                (
                    jnp.full(spec.cache_shape, i + 1, spec.dtype),
                    jnp.full(spec.cache_shape, -(i + 1), spec.dtype),
                )
                for _ in range(spec.num_layers)
            ]
            asyncio.run(cluster.save(p, caches, np.array([0, 1], np.int32)))
        owners = [cluster.owner_index(p) for p in prompts]
        print("owner per prompt:", owners)
        print("members used:", sorted(set(owners)))

        hits = sum(cluster.lookup(p) for p in prompts)
        print(f"blocks cached across the pool: {hits}")

        # Drain one member: only its prompts degrade to misses.
        victim = owners[0]
        servers[victim].stop()
        after = [cluster.lookup(p) for p in prompts]
        lost = sum(1 for o, h in zip(owners, after) if o == victim and h == 0)
        kept = sum(1 for o, h in zip(owners, after) if o != victim and h == 2)
        print(
            f"after stopping member {victim}: {lost} prompts degraded to "
            f"miss, {kept} still fully cached, degraded_ops="
            f"{cluster.degraded_ops}"
        )
        # The self-healing layer's attribution (docs/robustness.md): the
        # dead member's breaker opens after a few errors (later ops
        # fast-fail locally instead of burning timeouts), and health()
        # names the sick node. With replicas=2 the same drain would cost
        # NOTHING: saves mirror to the rendezvous runner-up and reads fail
        # over to it (see tests/test_selfheal.py).
        for m in cluster.health()["members"]:
            print(
                f"  {m['member_id']}: breaker={m['breaker_state']} "
                f"errors={m['errors']} fast_fails={m['fast_fails']} "
                f"degraded_ops={m['degraded_ops']}"
            )
    finally:
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass


if __name__ == "__main__":
    main()
