"""Fully-async single connection: connect_async, then write/read one batched
op per iteration in a loop (reference example/client_async_single.py's
connect-loop shape, minus its blocking-connect FIXME — ours awaits).
"""

import asyncio
import uuid

import numpy as np

from common import make_connection, parse_args

import infinistore_tpu as its


async def run(args):
    conn, cleanup = make_connection(args)
    await conn.connect_async()  # non-blocking connect inside the loop
    try:
        n_blocks, block = 16, 64 << 10
        src = np.random.randint(0, 256, size=n_blocks * block, dtype=np.uint8)
        dst = np.zeros_like(src)
        conn.register_mr(src)
        conn.register_mr(dst)
        for it in range(5):
            run_id = uuid.uuid4().hex[:8]
            blocks = [(f"as-{run_id}-{i}", i * block) for i in range(n_blocks)]
            await conn.write_cache_async(blocks, block, src.ctypes.data)
            await conn.read_cache_async(blocks, block, dst.ctypes.data)
            assert np.array_equal(src, dst)
            conn.delete_keys([k for k, _ in blocks])
            print(f"iteration {it}: {n_blocks} blocks round-tripped")
    finally:
        cleanup()


if __name__ == "__main__":
    asyncio.run(run(parse_args()))
