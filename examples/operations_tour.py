"""Operational features tour: spill tier, auto-reconnect, shaped striping,
QoS service classes.

Self-contained (starts its own servers); each section prints what it proves.

  python examples/operations_tour.py
"""

import asyncio
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its  # noqa: E402

BLOCK = 64 << 10


def spill_tier():
    """Capacity beyond RAM: 8MB of KV blocks through a 4MB pool."""
    srv = its.start_local_server(
        prealloc_bytes=4 << 20, block_bytes=BLOCK,
        spill_dir="/tmp", spill_bytes=64 << 20,
    )
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    n = 128
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    for i in range(n):
        c.write_cache([(f"kv-{i}", i * BLOCK)], BLOCK, src.ctypes.data)
    spill = c.get_stats()["spill"]
    dst = np.zeros(BLOCK, dtype=np.uint8)
    c.register_mr(dst)
    ok = 0
    for i in range(n):
        c.read_cache([(f"kv-{i}", 0)], BLOCK, dst.ctypes.data)
        ok += int(np.array_equal(dst, src[i * BLOCK : (i + 1) * BLOCK]))
    print(f"[spill] {n} blocks through a 64-block pool: {spill['entries']} demoted "
          f"to file, {ok}/{n} read back byte-exact "
          f"(promotions={c.get_stats()['spill']['promotions']})")
    c.close()
    srv.stop()


def auto_reconnect():
    """A restarted store looks like a cold cache, never a dead engine."""
    srv = its.start_local_server(prealloc_bytes=16 << 20, block_bytes=16 << 10)
    port = srv.port
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=port,
                         log_level="error", enable_shm=False, auto_reconnect=True)
    )
    c.connect()
    buf = np.full(16 << 10, 7, dtype=np.uint8)
    c.register_mr(buf)
    c.write_cache([("pre-restart", 0)], buf.nbytes, buf.ctypes.data)
    srv.stop()
    for _ in range(30):
        try:
            srv = its.start_local_server(host="127.0.0.1", service_port=port,
                                         prealloc_bytes=16 << 20, block_bytes=16 << 10)
            break
        except its.InfiniStoreException:
            time.sleep(0.1)
    # The next op transparently reconnects; the restarted store is cold.
    print(f"[reconnect] after restart: key present = {c.check_exist('pre-restart')} "
          f"(cold cache), connection live = {c.is_connected}")
    c.write_cache([("post-restart", 0)], buf.nbytes, buf.ctypes.data)
    print("[reconnect] writes work again with re-registered MRs — no manual recovery")
    c.close()
    srv.stop()


def shaped_striping():
    """Striping ~Nx when each connection is bandwidth-capped (cross-host
    emulation via SO_MAX_PACING_RATE)."""
    cap = 50
    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK,
                                 enable_shm=False, pacing_rate_mbps=cap)
    from infinistore_tpu.shaping import shaped_roundtrip_mbps

    one, _ = shaped_roundtrip_mbps(srv.port, cap, 1, nbytes=8 << 20, key_prefix="t1")
    four, _ = shaped_roundtrip_mbps(srv.port, cap, 4, nbytes=8 << 20, key_prefix="t4")
    print(f"[striping] per-conn cap {cap} MB/s: 1 stream = {one:.0f} MB/s, "
          f"4 stripes = {four:.0f} MB/s ({four / one:.1f}x)")
    srv.stop()


def quantized_cache():
    """int8 KV blocks: half the store bytes per block, dequantized loads
    within the scheme's tolerance (tpu/kv_quant.py)."""
    import asyncio

    import jax.numpy as jnp
    import numpy as np

    from infinistore_tpu.tpu import (
        PagedKVCacheSpec, QuantizedKVConnector, dequantize_kv, quantize_kv,
    )

    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=16 << 10)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port,
                         log_level="error")
    )
    c.connect()
    try:
        spec = PagedKVCacheSpec(
            num_layers=2, num_blocks=8, block_tokens=8, num_kv_heads=2,
            head_dim=64, dtype=jnp.float32,
        )
        qc = QuantizedKVConnector(c, spec, "tour", max_blocks=4)
        rng = np.random.default_rng(0)
        float_caches = [
            (jnp.asarray(rng.standard_normal(spec.cache_shape), jnp.float32),
             jnp.asarray(rng.standard_normal(spec.cache_shape), jnp.float32))
            for _ in range(spec.num_layers)
        ]
        quant = [
            (quantize_kv(k), quantize_kv(v)) for k, v in float_caches
        ]
        tokens = list(range(16))
        asyncio.run(qc.save(tokens, quant, np.array([0, 1], np.int32)))
        float_bytes = 2 * spec.num_layers * 2 * spec.block_nbytes
        data_bytes = float_bytes // 4  # f32 -> int8
        scale_bytes = data_bytes // spec.head_dim * 4
        err = float(
            jnp.abs(dequantize_kv(*quant[0][0]) - float_caches[0][0]).max()
        )
        print(f"[quant] 2 blocks x 2 layers: float {float_bytes} B -> int8+scales "
              f"{data_bytes + scale_bytes} B stored; max dequant err {err:.4f}; "
              f"lookup hits {qc.lookup(tokens)} blocks")
    finally:
        c.close()
        srv.stop()


def qos_classes():
    """Two-class QoS (docs/qos.md): tag a bulk save BACKGROUND so it yields
    to decode-critical reads, then read the per-class ledger back from both
    sides of the wire."""
    from infinistore_tpu import wire

    srv = its.start_local_server(prealloc_bytes=64 << 20, block_bytes=BLOCK)
    c = its.InfinityConnection(
        its.ClientConfig(host_addr="127.0.0.1", service_port=srv.port, log_level="error")
    )
    c.connect()
    n = 32
    src = np.random.randint(0, 256, size=n * BLOCK, dtype=np.uint8)
    c.register_mr(src)
    pairs = [(f"qos-{i}", i * BLOCK) for i in range(n)]

    async def tour():
        # A prefill save is never decode-blocking: tag it BACKGROUND and it
        # defers to foreground traffic in every queue it crosses (client
        # gate, stripe pulls, server slice scheduler) — KVConnector.save
        # does this automatically.
        await c.write_cache_async(
            pairs, BLOCK, src.ctypes.data, priority=wire.PRIORITY_BACKGROUND
        )
        # Untagged = FOREGROUND: byte-identical to the pre-QoS wire format.
        await c.read_cache_async(pairs[:4], BLOCK, src.ctypes.data)

    asyncio.run(tour())
    client = c.qos_stats()
    server_side = c.get_stats()["qos"]
    print(f"[qos] client ledger: fg_ops={client['fg_ops']} bg_ops={client['bg_ops']} "
          f"bg_deferred={client['bg_deferred']}")
    print(f"[qos] server ledger: fg_ops={server_side['fg_ops']} "
          f"bg_ops={server_side['bg_ops']} "
          f"bg_preempted_slices={server_side['bg_preempted_slices']} "
          f"bg_aged_slices={server_side['bg_aged_slices']}")
    c.close()
    srv.stop()


def main():
    spill_tier()
    auto_reconnect()
    shaped_striping()
    quantized_cache()
    qos_classes()


if __name__ == "__main__":
    main()
