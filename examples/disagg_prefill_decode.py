"""Prefill->decode disaggregation through the store — the flagship flow
(reference scenario 1, README.md:13-14, served there by vLLM+LMCache; here the
demo paged-KV Llama plays the engine on both sides).

Prefill 'host': runs the prompt, streams per-layer KV blocks to the store.
Decode 'host': fetches the blocks into its own cache layout and continues
generating, never having seen the prompt computation.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from common import get_connection, parse_args

from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill
from infinistore_tpu.tpu import (
    HostStagingPool,
    LayerwiseKVReader,
    LayerwiseKVWriter,
    kv_block_key,
)


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        cfg = LlamaConfig(
            vocab=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=256, block_tokens=8, dtype=jnp.float32,
        )
        params = init_params(cfg, jax.random.PRNGKey(0))
        spec = cfg.kv_spec(num_blocks=32)
        n_prompt_blocks = 2
        pool = HostStagingPool(
            nbytes=4 * n_prompt_blocks * 2 * spec.block_nbytes,
            block_size=spec.block_nbytes,
            conn=conn,
        )
        key_fn = lambda l, k, i: kv_block_key("demo-llama", "req-hash-001", l, k, i)

        # --- prefill host ---
        prompt = jnp.arange(16, dtype=jnp.int32) % cfg.vocab
        table = jnp.array([4, 11], dtype=jnp.int32)
        _, caches = prefill(params, prompt, spec.make_caches(), table, cfg)
        writer = LayerwiseKVWriter(conn, pool, spec, max_blocks=n_prompt_blocks)
        written = asyncio.run(writer.write(caches, np.asarray(table), key_fn))
        print(f"prefill host: streamed {written} KV blocks to the store")

        # --- decode host (fresh process in real deployments) ---
        decode_table = jnp.array([0, 1, 2, 3], dtype=jnp.int32)
        reader = LayerwiseKVReader(conn, pool, spec, max_blocks=n_prompt_blocks)
        decode_caches = asyncio.run(
            reader.read(spec.make_caches(), np.asarray(decode_table[:2]), key_fn)
        )
        print("decode host: fetched prompt KV from the store")

        token, position = jnp.int32(1), 16
        generated = []
        for step in range(8):
            logits, decode_caches = decode_step(
                params, token, jnp.int32(position), decode_caches, decode_table,
                cfg, 4,
            )
            token = jnp.argmax(logits).astype(jnp.int32)
            generated.append(int(token))
            position += 1
        print("decode host: generated tokens", generated)
    finally:
        cleanup()


if __name__ == "__main__":
    main()
