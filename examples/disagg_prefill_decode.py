"""Prefill->decode disaggregation through the store — the flagship flow
(reference scenario 1, README.md:13-14, served there by vLLM+LMCache; here the
demo paged-KV Llama plays the engine on both sides).

Prefill 'host': runs the prompt, streams per-layer KV blocks to the store.
Decode 'host': fetches the blocks into its own cache layout and continues
generating, never having seen the prompt computation.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from common import get_connection, parse_args

from infinistore_tpu.models import LlamaConfig, decode_step, init_params, prefill
from infinistore_tpu.tpu import (
    HostStagingPool,
    LayerwiseKVReader,
    LayerwiseKVWriter,
    kv_block_key,
)


def build(conn):
    cfg = LlamaConfig(
        vocab=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=256, block_tokens=8, dtype=jnp.float32,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))  # deterministic: both
    spec = cfg.kv_spec(num_blocks=32)                 # roles derive the same
    n_prompt_blocks = 2
    pool = HostStagingPool(
        nbytes=4 * n_prompt_blocks * 2 * spec.block_nbytes,
        block_size=spec.block_nbytes,
        conn=conn,
    )
    key_fn = lambda l, k, i: kv_block_key("demo-llama", "req-hash-001", l, k, i)
    return cfg, params, spec, n_prompt_blocks, pool, key_fn


def run_prefill(conn):
    cfg, params, spec, n_blocks, pool, key_fn = build(conn)
    prompt = jnp.arange(16, dtype=jnp.int32) % cfg.vocab
    table = jnp.array([4, 11], dtype=jnp.int32)
    _, caches = prefill(params, prompt, spec.make_caches(), table, cfg)
    writer = LayerwiseKVWriter(conn, pool, spec, max_blocks=n_blocks)
    written = asyncio.run(writer.write(caches, np.asarray(table), key_fn))
    print(f"prefill host: streamed {written} KV blocks to the store")


def run_decode(conn):
    cfg, params, spec, n_blocks, pool, key_fn = build(conn)
    decode_table = jnp.array([0, 1, 2, 3], dtype=jnp.int32)
    reader = LayerwiseKVReader(conn, pool, spec, max_blocks=n_blocks)
    decode_caches = asyncio.run(
        reader.read(spec.make_caches(), np.asarray(decode_table[:2]), key_fn)
    )
    print("decode host: fetched prompt KV from the store")

    token, position = jnp.int32(1), 16
    generated = []
    for _ in range(8):
        logits, decode_caches = decode_step(
            params, token, jnp.int32(position), decode_caches, decode_table, cfg, 4,
        )
        token = jnp.argmax(logits).astype(jnp.int32)
        generated.append(int(token))
        position += 1
    print("decode host: generated tokens", generated)


def main():
    import argparse
    import sys

    # Extra --role flag on top of the shared example args. In a real
    # deployment prefill and decode are separate hosts: run this script twice
    # against one server, `--role prefill` then `--role decode`.
    extra = argparse.ArgumentParser(add_help=False)
    extra.add_argument("--role", choices=["both", "prefill", "decode"], default="both")
    ns, rest = extra.parse_known_args()
    sys.argv = [sys.argv[0]] + rest
    args = parse_args()
    if ns.role != "both" and args.service_port == 0:
        raise SystemExit("--role prefill/decode needs --service-port of a shared server")
    conn, cleanup = get_connection(args)
    try:
        if ns.role in ("both", "prefill"):
            run_prefill(conn)
        if ns.role in ("both", "decode"):
            run_decode(conn)
    finally:
        cleanup()


if __name__ == "__main__":
    main()
