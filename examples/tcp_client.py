"""Single-key TCP put/get loop (reference example/tcp_client.py: 1000 keys
over the simple TCP path)."""

import numpy as np

from common import get_connection, parse_args


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        n = 1000
        data = np.random.randint(0, 256, size=4096, dtype=np.uint8)
        for i in range(n):
            conn.tcp_write_cache(f"tcp-{i}", data.ctypes.data, data.nbytes)
        print(f"put {n} keys")
        for i in range(n):
            out = conn.tcp_read_cache(f"tcp-{i}")
            assert np.array_equal(out, data)
        print(f"got {n} keys, verified")
    finally:
        cleanup()


if __name__ == "__main__":
    main()
