"""Continuous-batching engine serving through the store — BASELINE config 4
in miniature (the reference's production role: serving vLLM through LMCache,
reference README.md:22).

A ContinuousBatchingHarness drives the EngineKVAdapter the way a vLLM-TPU
engine would: concurrent requests drawing physical blocks from one shared
paged cache, an admission-time prefix probe per request, loads that skip
recompute for cached prefixes, suffix compute with the demo Llama, and
suffix-only writebacks. Prints the engine-side scoreboard: hit rate,
admission latency, recompute seconds saved.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from common import get_connection, parse_args

from infinistore_tpu import ContinuousBatchingHarness, EngineKVAdapter, KVConnector
from infinistore_tpu.engine import NGramDrafter
from infinistore_tpu.models import LlamaConfig, init_params


def main():
    args = parse_args()
    conn, cleanup = get_connection(args)
    try:
        cfg = LlamaConfig(
            vocab=256, dim=128, n_layers=4, n_heads=4, n_kv_heads=2,
            ffn_dim=256, block_tokens=16, dtype=jnp.float32,
        )
        num_blocks, req_blocks = 32, 4
        params = init_params(cfg, jax.random.PRNGKey(0))
        kvc = KVConnector(
            conn, cfg.kv_spec(num_blocks), "engine-demo", max_blocks=req_blocks
        )
        harness = ContinuousBatchingHarness(
            EngineKVAdapter(kvc), params, cfg, num_blocks, req_blocks,
            verify=True,  # every request checked against the prefill oracle
            # Speculative decoding in the serving loop: prompt-lookup
            # drafts verified inside the lockstep waves. Greedy output is
            # identical with or without it — only the round count drops.
            drafter=NGramDrafter(max_draft=4),
        )

        # Three prompt "families" sharing nothing with each other; requests
        # within a family share everything (think: repeated system prompts).
        # Mildly repetitive content gives the n-gram drafter footholds.
        rng = np.random.default_rng(7)
        families = []
        for _ in range(3):
            pat = rng.integers(0, cfg.vocab, size=5).tolist()
            families.append(
                (pat * ((req_blocks - 1) * cfg.block_tokens))[
                    : (req_blocks - 1) * cfg.block_tokens
                ]
            )
        workload = [families[i % 3] for i in range(12)]

        # Each request also GENERATES a few greedy tokens: concurrent
        # requests advance in lockstep batched waves (decode_waves /
        # max_wave_size below).
        metrics = asyncio.run(
            harness.run(workload, concurrency=4, gen_tokens=cfg.block_tokens)
        )
        print("engine-side scoreboard:")
        for k in (
            "requests", "hit_rate", "loaded_blocks", "computed_blocks",
            "raced_evictions", "p50_admission_us", "p99_admission_us",
            "p50_store_io_us", "p50_gate_stall_us",
            # Two-phase admission overlap: store fetch runs gate-free at
            # enqueue; only the short install holds the gate.
            "p50_gate_hold_us", "overlap_fraction", "prefetch_waste",
            "p50_prefix_ready_hit_us", "p50_prefix_ready_miss_us",
            "recompute_saved_s", "max_live_requests", "decode_waves",
            "max_wave_size", "generated_tokens", "spec_tokens_per_step",
            "spec_acceptance_rate", "all_verified",
        ):
            v = metrics[k]
            print(f"  {k}: {v:.3f}" if isinstance(v, float) else f"  {k}: {v}")
        assert metrics["all_verified"]
        assert metrics["hit_rate"] > 0, "repeat admissions should hit"
    finally:
        cleanup()


if __name__ == "__main__":
    main()
