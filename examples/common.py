"""Shared example plumbing: connect to a running server, or spin up an
in-process one so every example is self-contained (the reference examples
assume `infinistore` is already running on localhost;
reference example/client.py)."""

import argparse
import os
import sys

# Allow running straight from a repo checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--service-port", type=int, default=0,
        help="port of a running server; 0 = start one in-process",
    )
    return p.parse_args()


def make_connection(args):
    """Build (but do not connect) a client, starting an in-process server if
    no --service-port was given. For async examples that `await
    conn.connect_async()` themselves."""
    srv = None
    port = args.service_port
    if port == 0:
        srv = its.start_local_server()
        port = srv.port
        print(f"(started in-process server on :{port})")
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr=args.host, service_port=port)
    )

    def cleanup():
        conn.close()
        if srv is not None:
            srv.stop()

    return conn, cleanup


def get_connection(args):
    conn, cleanup = make_connection(args)
    conn.connect()
    return conn, cleanup
