"""Shared example plumbing: connect to a running server, or spin up an
in-process one so every example is self-contained (the reference examples
assume `infinistore` is already running on localhost;
/root/reference/infinistore/example/client.py)."""

import argparse
import os
import sys

# Allow running straight from a repo checkout without installation.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import infinistore_tpu as its
from infinistore_tpu._native import lib


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--service-port", type=int, default=0,
        help="port of a running server; 0 = start one in-process",
    )
    return p.parse_args()


def get_connection(args):
    handle = None
    port = args.service_port
    if port == 0:
        handle = lib.its_server_create(
            b"127.0.0.1", 0, 256 << 20, 64 << 10, 0, 0, 0, 0.8, 0.95
        )
        assert handle and lib.its_server_start(handle) == 0
        port = lib.its_server_port(handle)
        print(f"(started in-process server on :{port})")
    conn = its.InfinityConnection(
        its.ClientConfig(host_addr=args.host, service_port=port)
    )
    conn.connect()

    def cleanup():
        conn.close()
        if handle is not None:
            lib.its_server_stop(handle)
            lib.its_server_destroy(handle)

    return conn, cleanup
